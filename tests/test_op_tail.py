"""Op-tail coverage: newly added tensor ops, pooling mask/unpool, and
distribution edge cases (VERDICT r1 item 10 / SURVEY §4 OpTest row)."""
import numpy as np
import pytest
import scipy.special as sps
import scipy.spatial.distance as ssd

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F

R = np.random.RandomState(7)


def _fd_grad(fn, x, eps=1e-4):
    g = np.zeros_like(x)
    it = np.nditer(x, flags=["multi_index"])
    while not it.finished:
        i = it.multi_index
        xp = x.copy(); xp[i] += eps
        xm = x.copy(); xm[i] -= eps
        g[i] = (fn(xp) - fn(xm)) / (2 * eps)
        it.iternext()
    return g


def test_pdist_matches_scipy_and_grads():
    x = R.randn(5, 3).astype(np.float64)
    for p in (1.0, 2.0, 3.0, float("inf")):
        got = paddle.pdist(paddle.to_tensor(x), p=p).numpy()
        ref = ssd.pdist(x, "minkowski", p=p) if p != float("inf") \
            else ssd.pdist(x, "chebyshev")
        np.testing.assert_allclose(got, ref, rtol=1e-6)
    t = paddle.to_tensor(x.astype(np.float32)); t.stop_gradient = False
    loss = paddle.pdist(t).sum(); loss.backward()
    fd = _fd_grad(lambda a: ssd.pdist(a, "minkowski", p=2).sum(), x)
    np.testing.assert_allclose(t.grad.numpy(), fd, rtol=1e-3, atol=1e-4)


def test_logaddexp2_multigammaln_sgn():
    a, b = R.randn(4), R.randn(4)
    np.testing.assert_allclose(
        paddle.logaddexp2(paddle.to_tensor(a), paddle.to_tensor(b)).numpy(),
        np.logaddexp2(a, b), rtol=1e-6)
    x = R.uniform(2.0, 5.0, (6,))
    np.testing.assert_allclose(
        paddle.multigammaln(paddle.to_tensor(x), 3).numpy(),
        sps.multigammaln(x, 3), rtol=1e-5)
    v = np.array([-2.0, 0.0, 3.5])
    np.testing.assert_allclose(paddle.sgn(paddle.to_tensor(v)).numpy(),
                               np.sign(v))


def test_unflatten_view_as_as_strided():
    x = paddle.to_tensor(np.arange(24.0, dtype="float32"))
    assert paddle.unflatten(x.reshape([4, 6]), 1, [2, -1]).shape == [4, 2, 3]
    assert paddle.view_as(x, paddle.ones([4, 6])).shape == [4, 6]
    got = paddle.as_strided(x, [3, 4], [1, 3]).numpy()
    ref = np.lib.stride_tricks.as_strided(
        np.arange(24.0, dtype="float32"), (3, 4), (4, 12))
    np.testing.assert_allclose(got, ref)


def test_max_pool_mask_and_unpool_roundtrip():
    x = R.randn(2, 3, 8, 8).astype(np.float32)
    t = paddle.to_tensor(x)
    out, mask = F.max_pool2d(t, 2, return_mask=True)
    # mask agrees with a numpy argmax per window
    for n in range(2):
        for c in range(3):
            for i in range(4):
                for j in range(4):
                    win = x[n, c, 2 * i:2 * i + 2, 2 * j:2 * j + 2]
                    fi = int(mask.numpy()[n, c, i, j])
                    assert x[n, c].ravel()[fi] == win.max()
    rec = F.max_unpool2d(out, mask, 2)
    assert rec.shape == [2, 3, 8, 8]
    # unpooled holds the max at its original position, zeros elsewhere
    nz = rec.numpy() != 0
    assert nz.sum() == 2 * 3 * 16
    np.testing.assert_allclose(rec.numpy()[nz],
                               np.sort(out.numpy().ravel())[
                                   np.argsort(np.argsort(rec.numpy()[nz]))],
                               rtol=1e-6)


def test_max_unpool_gradient_routes_to_max_positions():
    x = R.randn(1, 1, 4, 4).astype(np.float32)
    t = paddle.to_tensor(x); t.stop_gradient = False
    out, mask = F.max_pool2d(t, 2, return_mask=True)
    rec = F.max_unpool2d(out, mask, 2)
    rec.sum().backward()
    g = t.grad.numpy()[0, 0]
    # exactly the 4 max positions get gradient 1
    assert (g == 1).sum() == 4 and (g != 0).sum() == 4


def test_lp_pool_values():
    x = np.abs(R.randn(1, 1, 4, 4)).astype(np.float32)
    got = F.lp_pool2d(paddle.to_tensor(x), 2, 2).numpy()[0, 0]
    for i in range(2):
        for j in range(2):
            win = x[0, 0, 2 * i:2 * i + 2, 2 * j:2 * j + 2]
            np.testing.assert_allclose(got[i, j],
                                       np.sqrt((win ** 2).sum()), rtol=1e-5)


def test_threshold_zeropad_feature_alpha_dropout():
    x = paddle.to_tensor(np.array([[-1.0, 0.5, 2.0]], np.float32))
    np.testing.assert_allclose(
        F.threshold(x, 1.0, -7.0).numpy(), [[-7.0, -7.0, 2.0]])
    im = paddle.ones([1, 1, 2, 2])
    z = F.zeropad2d(im, [1, 0, 0, 2])
    assert z.shape == [1, 1, 4, 3] and float(z.numpy().sum()) == 4.0
    paddle.seed(11)
    fad = F.feature_alpha_dropout(paddle.ones([2, 8, 4]), p=0.5)
    arr = fad.numpy()
    # whole channels share one fate: within-channel variance is zero
    assert np.allclose(arr.std(axis=-1), 0.0, atol=1e-6)
    # statistics preserved approximately (mean near 1 for unit input)
    assert abs(arr.mean() - 1.0) < 0.6


# -- distribution edge cases (ref: test/distribution/*) ---------------------

def test_distribution_edge_cases():
    from paddle_tpu.distribution import (Bernoulli, Categorical, Normal,
                                         Uniform)
    # Normal: cdf extremes saturate without NaN
    n = Normal(paddle.to_tensor(0.0), paddle.to_tensor(1.0))
    big = n.cdf(paddle.to_tensor(50.0)).numpy()
    small = n.cdf(paddle.to_tensor(-50.0)).numpy()
    assert big == pytest.approx(1.0, abs=1e-6)
    assert small == pytest.approx(0.0, abs=1e-6)
    # log_prob far in the tail is finite
    assert np.isfinite(n.log_prob(paddle.to_tensor(40.0)).numpy())

    # Categorical with a zero-probability class: sampled never, log_prob -inf
    probs = paddle.to_tensor(np.array([0.5, 0.5, 0.0], np.float32))
    c = Categorical(probs)
    paddle.seed(5)
    s = c.sample([512]).numpy()
    assert (s == 2).sum() == 0
    lp = c.log_prob(paddle.to_tensor(np.array([2], np.int64))).numpy()
    assert np.isneginf(lp) or lp < -20

    # Bernoulli p=0 / p=1 degenerate
    b0 = Bernoulli(paddle.to_tensor(0.0))
    b1 = Bernoulli(paddle.to_tensor(1.0))
    paddle.seed(6)
    assert b0.sample([64]).numpy().sum() == 0
    assert b1.sample([64]).numpy().sum() == 64

    # Uniform: log_prob outside support
    u = Uniform(paddle.to_tensor(0.0), paddle.to_tensor(1.0))
    out = u.log_prob(paddle.to_tensor(2.0)).numpy()
    assert np.isneginf(out) or out < -20


def test_entropy_kl_consistency():
    from paddle_tpu.distribution import Normal, kl_divergence
    a = Normal(paddle.to_tensor(0.0), paddle.to_tensor(1.0))
    b = Normal(paddle.to_tensor(0.0), paddle.to_tensor(1.0))
    np.testing.assert_allclose(kl_divergence(a, b).numpy(), 0.0, atol=1e-6)
    c = Normal(paddle.to_tensor(1.0), paddle.to_tensor(2.0))
    kl = kl_divergence(a, c).numpy()
    ref = np.log(2.0) + (1 + 1) / (2 * 4) - 0.5
    np.testing.assert_allclose(kl, ref, rtol=1e-5)


def test_round3_tensor_op_tail():
    """Round-3 long-tail additions: unfold/multiplex/shape/rank/is_empty/
    broadcast_shape/floor_mod/tolist/randint_like."""
    x = paddle.to_tensor(np.arange(12, dtype=np.float32).reshape(3, 4))
    assert paddle.broadcast_shape([3, 1], [1, 4]) == [3, 4]
    np.testing.assert_allclose(
        paddle.floor_mod(paddle.to_tensor([7.0, -7.0]),
                         paddle.to_tensor([3.0, 3.0])).numpy(),
        [1.0, 2.0])  # python-style mod (the reference's floor_mod)
    assert not bool(paddle.is_empty(x).numpy())
    assert bool(paddle.is_empty(
        paddle.to_tensor(np.zeros((0, 3), np.float32))).numpy())
    assert int(paddle.rank(x).numpy()) == 2
    np.testing.assert_array_equal(paddle.shape(x).numpy(), [3, 4])
    assert paddle.tolist(x)[2][3] == 11.0

    u = paddle.unfold(paddle.to_tensor(np.arange(8, dtype=np.float32)),
                      0, 3, 2)
    np.testing.assert_array_equal(u.numpy(), [[0, 1, 2], [2, 3, 4],
                                              [4, 5, 6]])
    # unfold on a middle axis keeps surrounding dims, window last
    u2 = paddle.unfold(x, 1, 2, 2)
    assert u2.shape == [3, 2, 2]
    np.testing.assert_array_equal(u2.numpy()[0], [[0, 1], [2, 3]])

    m = paddle.multiplex(
        [paddle.to_tensor(np.full((3, 2), 7, np.float32)),
         paddle.to_tensor(np.zeros((3, 2), np.float32))],
        paddle.to_tensor(np.array([[0], [1], [0]], np.int32)))
    np.testing.assert_array_equal(m.numpy()[:, 0], [7, 0, 7])

    r = paddle.randint_like(x, 5, 10)
    assert r.shape == [3, 4]
    assert (np.asarray(r.numpy()) >= 5).all() and (np.asarray(r.numpy()) < 10).all()


def test_round3_linalg_tail():
    a = paddle.to_tensor(np.array([[2.0, 0.0], [0.0, 4.0]], np.float32))
    np.testing.assert_allclose(paddle.linalg.cond(a).numpy(), 2.0,
                               rtol=1e-5)
    np.testing.assert_allclose(paddle.linalg.cond(a, "fro").numpy(),
                               np.sqrt(20) * np.sqrt(0.25 + 1 / 16),
                               rtol=1e-5)
    np.testing.assert_allclose(paddle.linalg.cond(a, 1).numpy(), 2.0,
                               rtol=1e-5)
    np.testing.assert_allclose(paddle.linalg.inv(a).numpy(),
                               [[0.5, 0], [0, 0.25]], rtol=1e-6)


def test_round3_functional_tail():
    import paddle_tpu.nn.functional as F
    # adaptive_max_pool1d incl. mask
    xin = paddle.to_tensor(np.arange(12, dtype=np.float32).reshape(1, 2, 6))
    p1, mask = F.adaptive_max_pool1d(xin, 3, return_mask=True)
    np.testing.assert_array_equal(p1.numpy()[0, 0], [1, 3, 5])
    np.testing.assert_array_equal(mask.numpy()[0, 0], [1, 3, 5])

    # gather_tree: hand-checked backtrack
    ids = paddle.to_tensor(np.array([[[2, 5]], [[6, 1]], [[3, 9]]], np.int32))
    par = paddle.to_tensor(np.array([[[0, 0]], [[1, 0]], [[0, 1]]], np.int32))
    out = F.gather_tree(ids, par).numpy()
    # beam 0 at t=2 came from parent 0 (t=1, id 6) whose parent is 1 (t=0,
    # id 5); beam 1 came from parent 1 (t=1, id 1) whose parent is 0
    np.testing.assert_array_equal(out[:, 0, 0], [5, 6, 3])
    np.testing.assert_array_equal(out[:, 0, 1], [2, 1, 9])

    # triplet_margin_with_distance_loss: custom distance
    x = paddle.to_tensor(np.ones((4, 8), np.float32))
    loss = F.triplet_margin_with_distance_loss(
        x, x, x + 2.0, distance_function=lambda a, b: ((a - b) ** 2).sum(-1),
        margin=1.0)
    assert float(loss.numpy()) == 0.0  # d_neg=32 >> d_pos+margin

    # hsigmoid_loss: finite, positive, grads flow, works for non-pow2
    rng = np.random.RandomState(0)
    w = paddle.to_tensor(rng.randn(9, 4).astype(np.float32) * 0.1)
    w.stop_gradient = False
    xs = paddle.to_tensor(rng.randn(5, 4).astype(np.float32))
    lbl = paddle.to_tensor(np.array([0, 3, 9, 5, 7], np.int64))
    hs = F.hsigmoid_loss(xs, lbl, 10, w)
    assert hs.shape == [5, 1] and (hs.numpy() > 0).all()
    hs.sum().backward()
    assert np.abs(w.grad.numpy()).sum() > 0
    # custom path tables give the same result as the default tree when
    # they ENCODE the default tree
    codes = np.array([[(lbl_ + 10) >> s for s in range(1, 5)]
                      for lbl_ in [0, 3, 9, 5, 7]])
    tbl = np.where(codes > 0, codes - 1, -1).astype(np.int64)
    bits = np.array([[((lbl_ + 10) >> (s - 1)) & 1 for s in range(1, 5)]
                     for lbl_ in [0, 3, 9, 5, 7]]).astype(np.int64)
    hs2 = F.hsigmoid_loss(xs, lbl, 10, w, path_table=paddle.to_tensor(tbl),
                          path_code=paddle.to_tensor(bits))
    np.testing.assert_allclose(hs.numpy(), hs2.numpy(), rtol=1e-5)

    # sparse_attention equals dense attention restricted to the pattern
    q = paddle.to_tensor(rng.randn(1, 1, 3, 4).astype(np.float32))
    k = paddle.to_tensor(rng.randn(1, 1, 3, 4).astype(np.float32))
    v = paddle.to_tensor(rng.randn(1, 1, 3, 4).astype(np.float32))
    # row 0 -> {0,1}; row 1 -> {1}; row 2 -> {0,2}
    off = paddle.to_tensor(np.array([[[0, 2, 3, 5]]], np.int32))
    cols = paddle.to_tensor(np.array([[[0, 1, 1, 0, 2]]], np.int32))
    got = F.sparse_attention(q, k, v, off, cols).numpy()[0, 0]
    qn, kn, vn = (t.numpy()[0, 0] for t in (q, k, v))
    lg = qn @ kn.T / 2.0
    mask = np.array([[1, 1, 0], [0, 1, 0], [1, 0, 1]], bool)
    lg = np.where(mask, lg, -1e30)
    p = np.exp(lg - lg.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    np.testing.assert_allclose(got, p @ vn, rtol=1e-4, atol=1e-5)


def test_round3_tensor_method_surface():
    """Method-form parity (reference math_op_patch): tril/triu/diag/where/
    in-place random fills/add_n attach to Tensor."""
    x = paddle.to_tensor(np.arange(6, dtype=np.float32).reshape(2, 3))
    assert x.tril().numpy()[0, 1] == 0 and x.triu().numpy()[1, 0] == 0
    np.testing.assert_array_equal(
        paddle.to_tensor(np.array([1.0, 2.0])).diag().numpy(),
        np.diag([1.0, 2.0]))
    c = paddle.to_tensor(np.array([True, False]))
    np.testing.assert_array_equal(
        c.where(paddle.to_tensor([1.0, 1.0]),
                paddle.to_tensor([2.0, 2.0])).numpy(), [1.0, 2.0])
    paddle.seed(0)
    y = paddle.to_tensor(np.zeros((64,), np.float32))
    y.uniform_(0.0, 1.0)
    assert (y.numpy() >= 0).all() and (y.numpy() <= 1).all() \
        and y.numpy().std() > 0
    z = paddle.to_tensor(np.zeros((256,), np.float32))
    z.normal_(5.0, 0.1)
    assert abs(z.numpy().mean() - 5.0) < 0.1
    w = paddle.to_tensor(np.zeros((8,), np.float32))
    w.bernoulli_(1.0)
    assert (w.numpy() == 1).all()
    e = paddle.to_tensor(np.zeros((512,), np.float32))
    e.exponential_(2.0)
    np.testing.assert_allclose(e.numpy().mean(), 0.5, rtol=0.5)
    u = paddle.to_tensor(np.zeros((2,), np.float32))
    u.unsqueeze_(0)
    assert u.shape == [1, 2]
    f = paddle.to_tensor(np.arange(4, dtype=np.float32).reshape(2, 2))
    f.flatten_()
    assert f.shape == [4]
    s = paddle.add_n([x, x, x])
    np.testing.assert_allclose(s.numpy(),
                               3 * np.arange(6).reshape(2, 3))


def test_inplace_methods_respect_autograd_protocol():
    """In-place fills/reshapes follow the same contract as __setitem__:
    leaf-requiring-grad refuses, and earlier consumers of the old value
    raise at backward (version check) instead of silently using stale
    residuals."""
    import pytest as _pytest
    # leaf with grad: refuse
    x = paddle.to_tensor(np.ones((3,), np.float32))
    x.stop_gradient = False
    with _pytest.raises(RuntimeError, match="leaf Tensor"):
        x.normal_()
    # version check: consumer recorded before the in-place write raises
    a = paddle.to_tensor(np.ones((3,), np.float32))
    a.stop_gradient = False
    t = a * 2.0            # non-leaf
    y = t * t              # consumer of t's OLD value
    t.uniform_()           # in-place rewrite of t
    with _pytest.raises(RuntimeError, match="in-place"):
        y.sum().backward()
    # deterministic seed
    u1 = paddle.to_tensor(np.zeros((8,), np.float32)); u1.uniform_(seed=7)
    u2 = paddle.to_tensor(np.zeros((8,), np.float32)); u2.uniform_(seed=7)
    np.testing.assert_array_equal(u1.numpy(), u2.numpy())
    # one-arg where (nonzero indices) still works on the method
    c = paddle.to_tensor(np.array([0.0, 3.0, 0.0, 5.0]))
    nz = (c != 0.0).where()
    assert [int(v) for v in np.asarray(nz[0].numpy()).ravel()] == [1, 3]


def test_inplace_fill_on_nonleaf_detaches():
    """A second in-place fill on a former non-leaf must not raise: the
    first fill disconnects it from the graph (stop_gradient True), same
    net effect as detach + fill."""
    a = paddle.to_tensor(np.ones((3,), np.float32))
    a.stop_gradient = False
    t = a * 2.0
    t.uniform_()
    t.normal_()          # second fill: no spurious leaf error
    assert t.stop_gradient


def test_weight_only_quant_ops():
    """paddle.nn.quant weight_quantize/dequantize/weight_only_linear parity
    (int8 and packed int4)."""
    from paddle_tpu.nn import quant as Q
    rng = np.random.RandomState(0)
    w = rng.randn(10, 6).astype(np.float32)
    x = rng.randn(4, 10).astype(np.float32)

    for algo, dt, tol in (("weight_only_int8", "int8", 2e-2),
                          ("weight_only_int4", "int4", 2e-1)):
        qw, sc = Q.weight_quantize(paddle.to_tensor(w), algo=algo)
        if algo == "weight_only_int8":
            assert qw.shape == [10, 6]
        else:
            assert qw.shape == [5, 6]  # two nibbles per byte along IN
        back = Q.weight_dequantize(qw, sc, algo=algo).numpy()
        np.testing.assert_allclose(back, w, atol=np.abs(w).max() * tol)
        y = Q.weight_only_linear(paddle.to_tensor(x), qw,
                                 bias=paddle.to_tensor(
                                     np.ones(6, np.float32)),
                                 weight_scale=sc, weight_dtype=dt).numpy()
        np.testing.assert_allclose(y, x @ back + 1.0, rtol=1e-4, atol=1e-4)

    # grads flow through the activation
    xt = paddle.to_tensor(x)
    xt.stop_gradient = False
    qw, sc = Q.weight_quantize(paddle.to_tensor(w))
    out = Q.weight_only_linear(xt, qw, weight_scale=sc)
    out.sum().backward()
    deq = Q.weight_dequantize(qw, sc).numpy()
    np.testing.assert_allclose(xt.grad.numpy(),
                               np.tile(deq.sum(-1), (4, 1)), rtol=1e-4)
