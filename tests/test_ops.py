"""Pallas kernel numerics vs XLA references (interpret mode on CPU)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu  # noqa: F401
from paddle_tpu.nn.functional.attention import _xla_sdpa
from paddle_tpu.ops.flash_attention import flash_attention_bshd
from paddle_tpu.ops.rms_norm import fused_rms_norm
from paddle_tpu.ops.rope import apply_rope, build_rope_cache


@pytest.fixture
def qkv():
    rng = np.random.RandomState(0)
    B, S, H, D = 2, 256, 4, 64
    mk = lambda: jnp.asarray(rng.randn(B, S, H, D), jnp.float32)
    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [False, True])
def test_flash_forward(qkv, causal):
    q, k, v = qkv
    o = flash_attention_bshd(q, k, v, causal=causal)
    ref = _xla_sdpa(q, k, v, is_causal=causal)
    np.testing.assert_allclose(np.asarray(o), np.asarray(ref), atol=2e-5)


def test_flash_backward(qkv):
    q, k, v = qkv
    gf = jax.grad(lambda *a: (flash_attention_bshd(*a, causal=True) ** 2).sum(),
                  argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(lambda *a: (_xla_sdpa(*a, is_causal=True) ** 2).sum(),
                  argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-3)


def test_flash_gqa(qkv):
    q, k, v = qkv
    kg, vg = k[:, :, :2], v[:, :, :2]
    o = flash_attention_bshd(q, kg, vg, causal=True)
    ref = _xla_sdpa(q, kg, vg, is_causal=True)
    np.testing.assert_allclose(np.asarray(o), np.asarray(ref), atol=2e-5)


def test_rms_norm_fwd_bwd():
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(8, 512), jnp.float32)
    w = jnp.asarray(rng.randn(512), jnp.float32)
    out = fused_rms_norm(x, w)
    ref = np.asarray(x) / np.sqrt((np.asarray(x) ** 2).mean(-1, keepdims=True)
                                  + 1e-6) * np.asarray(w)
    np.testing.assert_allclose(np.asarray(out), ref, atol=1e-5)

    ref_fn = lambda x: (x * jax.lax.rsqrt((x ** 2).mean(-1, keepdims=True)
                                          + 1e-6) * w).sum()
    gx = jax.grad(lambda x: fused_rms_norm(x, w).sum())(x)
    gx_ref = jax.grad(ref_fn)(x)
    np.testing.assert_allclose(np.asarray(gx), np.asarray(gx_ref), atol=1e-5)
    gw = jax.grad(lambda w_: fused_rms_norm(x, w_).sum())(w)
    gw_ref = jax.grad(lambda w_: (x * jax.lax.rsqrt(
        (x ** 2).mean(-1, keepdims=True) + 1e-6) * w_).sum())(w)
    np.testing.assert_allclose(np.asarray(gw), np.asarray(gw_ref), atol=1e-4)


def test_rope_properties():
    rng = np.random.RandomState(2)
    q = jnp.asarray(rng.randn(2, 64, 4, 32), jnp.float32)
    cos, sin = build_rope_cache(64, 32)
    qr = apply_rope(q, cos, sin)
    # rotation preserves norms
    np.testing.assert_allclose(np.asarray(jnp.linalg.norm(qr, axis=-1)),
                               np.asarray(jnp.linalg.norm(q, axis=-1)),
                               atol=1e-4)
    # position 0 is the identity
    np.testing.assert_allclose(np.asarray(qr[:, 0]), np.asarray(q[:, 0]),
                               atol=1e-6)
    # relative property: scores depend only on distance
    k = jnp.asarray(rng.randn(2, 64, 4, 32), jnp.float32)
    kr = apply_rope(k, cos, sin)
    s1 = float((qr[0, 10, 0] * kr[0, 5, 0]).sum())
    # shift both positions by 7
    q2 = jnp.roll(jnp.zeros_like(q).at[:, 10].set(q[:, 10]), 7, axis=1)
    # simpler: recompute with shifted caches
    cos2, sin2 = build_rope_cache(64, 32, position_ids=jnp.arange(64) + 7)
    qr2 = apply_rope(q, cos2, sin2)
    kr2 = apply_rope(k, cos2, sin2)
    s2 = float((qr2[0, 10, 0] * kr2[0, 5, 0]).sum())
    np.testing.assert_allclose(s1, s2, rtol=1e-4)


def test_ring_attention_matches_dense():
    from paddle_tpu._compat import shard_map
    from jax.sharding import Mesh, PartitionSpec as P
    from paddle_tpu.parallel.ring_attention import ring_attention
    devs = np.array(jax.devices("cpu")[:4])
    mesh = Mesh(devs, axis_names=("sep",))
    rng = np.random.RandomState(3)
    B, S, H, D = 2, 128, 4, 32
    q = jnp.asarray(rng.randn(B, S, H, D), jnp.float32)
    k = jnp.asarray(rng.randn(B, S, H, D), jnp.float32)
    v = jnp.asarray(rng.randn(B, S, H, D), jnp.float32)
    fn = shard_map(lambda q, k, v: ring_attention(q, k, v, causal=True),
                   mesh=mesh,
                   in_specs=(P(None, "sep"), P(None, "sep"), P(None, "sep")),
                   out_specs=P(None, "sep"), check_vma=False)
    out = fn(q, k, v)
    ref = _xla_sdpa(q, k, v, is_causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-4)


def test_ulysses_matches_dense():
    from paddle_tpu._compat import shard_map
    from jax.sharding import Mesh, PartitionSpec as P
    from paddle_tpu.parallel.ring_attention import ulysses_attention
    devs = np.array(jax.devices("cpu")[:4])
    mesh = Mesh(devs, axis_names=("sep",))
    rng = np.random.RandomState(4)
    B, S, H, D = 2, 128, 4, 32
    q = jnp.asarray(rng.randn(B, S, H, D), jnp.float32)
    k = jnp.asarray(rng.randn(B, S, H, D), jnp.float32)
    v = jnp.asarray(rng.randn(B, S, H, D), jnp.float32)
    fn = shard_map(lambda q, k, v: ulysses_attention(q, k, v, causal=True),
                   mesh=mesh,
                   in_specs=(P(None, "sep"), P(None, "sep"), P(None, "sep")),
                   out_specs=P(None, "sep"), check_vma=False)
    out = fn(q, k, v)
    ref = _xla_sdpa(q, k, v, is_causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-4)


@pytest.mark.parametrize("s,sk", [(300, 300), (1500, 1500), (384, 640)])
def test_flash_ragged_lengths(s, sk):
    """Sequence lengths that are not block multiples: zero-pad + mask path
    (regression: clamped pl.ds slices silently double-counted rows)."""
    rng = np.random.RandomState(5)
    B, H, D = 1, 2, 64
    q = jnp.asarray(rng.randn(B, s, H, D), jnp.float32)
    k = jnp.asarray(rng.randn(B, sk, H, D), jnp.float32)
    v = jnp.asarray(rng.randn(B, sk, H, D), jnp.float32)
    causal = s == sk
    o = flash_attention_bshd(q, k, v, causal=causal)
    ref = _xla_sdpa(q, k, v, is_causal=causal)
    np.testing.assert_allclose(np.asarray(o), np.asarray(ref), atol=2e-5)

    if causal:
        gf = jax.grad(lambda *a: (flash_attention_bshd(*a, causal=True)
                                  ** 2).sum(), argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(lambda *a: (_xla_sdpa(*a, is_causal=True) ** 2).sum(),
                      argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gf, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=2e-3)
