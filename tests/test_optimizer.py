"""Optimizer update-rule numerics vs closed-form references + schedulers."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.optimizer as opt


def _one_param_model(value):
    m = nn.Linear(1, 1, bias_attr=False)
    m.weight.set_value(np.array([[value]], np.float32))
    return m


def _step(m, o, grad_val):
    m.weight.grad = paddle.to_tensor(np.array([[grad_val]], np.float32))
    o.step()
    o.clear_grad()
    return float(m.weight.numpy()[0, 0])


def test_sgd():
    m = _one_param_model(1.0)
    o = opt.SGD(learning_rate=0.1, parameters=m.parameters())
    assert abs(_step(m, o, 0.5) - (1.0 - 0.1 * 0.5)) < 1e-6


def test_momentum_nesterov():
    m = _one_param_model(1.0)
    o = opt.Momentum(learning_rate=0.1, momentum=0.9,
                     parameters=m.parameters())
    w1 = _step(m, o, 1.0)          # v=1, w=1-0.1
    assert abs(w1 - 0.9) < 1e-6
    w2 = _step(m, o, 1.0)          # v=1.9, w=0.9-0.19
    assert abs(w2 - 0.71) < 1e-6


def test_adam_bias_correction():
    m = _one_param_model(1.0)
    o = opt.Adam(learning_rate=0.1, beta1=0.9, beta2=0.999,
                 parameters=m.parameters())
    w1 = _step(m, o, 2.0)
    # first step of adam moves by ~lr regardless of grad scale
    assert abs(w1 - (1.0 - 0.1)) < 1e-4


def test_adamw_decoupled_decay():
    m = _one_param_model(1.0)
    o = opt.AdamW(learning_rate=0.1, weight_decay=0.1,
                  parameters=m.parameters())
    w1 = _step(m, o, 0.0)
    # zero grad: only the decoupled decay applies (moments stay 0)
    assert abs(w1 - (1.0 - 0.1 * 0.1 * 1.0)) < 1e-5


def test_multi_precision_master_weights():
    m = nn.Linear(2, 2, bias_attr=False)
    m.bfloat16()
    o = opt.AdamW(learning_rate=1e-4, parameters=m.parameters(),
                  multi_precision=True)
    x = paddle.randn([4, 2]).astype("bfloat16")
    for _ in range(3):
        m(x).sum().backward()
        o.step()
        o.clear_grad()
    assert m.weight.dtype == paddle.bfloat16
    assert len(o._master_weights) == 1  # fp32 master kept


def test_multi_precision_moment_dtype_and_parity():
    """multi_precision=False stores Adam moments in the PARAM dtype
    (optimizer HBM halves on bf16); True (the default) keeps f32 moments.
    The update math is f32 either way, so a few steps on a bf16 param
    must agree within bf16 rounding of the moments."""
    import jax.numpy as jnp

    def run(multi_precision):
        paddle.seed(0)
        m = nn.Linear(4, 4, bias_attr=False)
        m.bfloat16()
        o = opt.AdamW(learning_rate=1e-2, parameters=m.parameters(),
                      multi_precision=multi_precision)
        x = paddle.ones([2, 4]).astype("bfloat16")
        for _ in range(5):
            m(x).astype("float32").sum().backward()
            o.step()
            o.clear_grad()
        st = list(o._accumulators.values())[0]
        return m.weight.numpy().astype(np.float32), st

    w_hi, st_hi = run(True)
    w_lo, st_lo = run(False)
    assert st_hi["moment1"].dtype == jnp.float32
    assert st_lo["moment1"].dtype == jnp.bfloat16
    # bf16 moments round each step; updates stay within a few bf16 ulps
    np.testing.assert_allclose(w_lo, w_hi, rtol=2e-2, atol=2e-2)
    # f32-param models are unaffected by the knob: moments match exactly
    def run_f32(mp):
        paddle.seed(0)
        m = _one_param_model(1.0)
        o = opt.Adam(learning_rate=0.1, parameters=m.parameters(),
                     multi_precision=mp)
        for _ in range(3):
            _step(m, o, 0.5)
        return m.weight.numpy()
    np.testing.assert_array_equal(run_f32(True), run_f32(False))


def test_param_groups():
    a, b = nn.Linear(2, 2), nn.Linear(2, 2)
    o = opt.SGD(learning_rate=0.1, parameters=[
        {"params": a.parameters()},
        {"params": b.parameters(), "learning_rate": 0.1},  # scale => lr*0.1
    ])
    wa0, wb0 = a.weight.numpy().copy(), b.weight.numpy().copy()
    g = np.ones((2, 2), np.float32)
    a.weight.grad = paddle.to_tensor(g)
    b.weight.grad = paddle.to_tensor(g)
    o.step()
    np.testing.assert_allclose(wa0 - a.weight.numpy(), 0.1 * g, atol=1e-6)
    np.testing.assert_allclose(wb0 - b.weight.numpy(), 0.01 * g, atol=1e-6)


def test_lr_schedulers():
    s = opt.lr.StepDecay(learning_rate=1.0, step_size=2, gamma=0.5)
    lrs = []
    for _ in range(5):
        lrs.append(s())
        s.step()
    assert lrs == [1.0, 1.0, 0.5, 0.5, 0.25]

    warm = opt.lr.LinearWarmup(learning_rate=1.0, warmup_steps=4,
                               start_lr=0.0, end_lr=1.0)
    vals = []
    for _ in range(5):
        vals.append(warm())
        warm.step()
    np.testing.assert_allclose(vals, [0.0, 0.25, 0.5, 0.75, 1.0])

    cos = opt.lr.CosineAnnealingDecay(learning_rate=1.0, T_max=10)
    assert abs(cos() - 1.0) < 1e-6

    noam = opt.lr.NoamDecay(d_model=64, warmup_steps=10, learning_rate=1.0)
    v = [noam() or 0]
    for _ in range(20):
        noam.step()
        v.append(noam())
    assert np.argmax(v) in (9, 10, 11)


def test_scheduler_with_optimizer_and_state():
    m = nn.Linear(2, 2)
    sched = opt.lr.ExponentialDecay(learning_rate=0.1, gamma=0.9)
    o = opt.Adam(learning_rate=sched, parameters=m.parameters())
    assert abs(o.get_lr() - 0.1) < 1e-9
    sched.step()
    assert abs(o.get_lr() - 0.09) < 1e-9
    sd = o.state_dict()
    assert "LR_Scheduler" in sd


def test_optimizer_state_roundtrip(tmp_path):
    m = nn.Linear(2, 2)
    o = opt.Adam(learning_rate=0.1, parameters=m.parameters())
    m(paddle.randn([2, 2])).sum().backward()
    o.step()
    path = str(tmp_path / "opt.pdopt")
    paddle.save(o.state_dict(), path)
    o2 = opt.Adam(learning_rate=0.1, parameters=m.parameters())
    o2.set_state_dict(paddle.load(path))
    k = list(o._accumulators)[0]
    np.testing.assert_allclose(
        np.asarray(o._accumulators[k]["moment1"]),
        np.asarray(o2._accumulators[k]["moment1"]))


def test_grad_clip_in_optimizer():
    m = _one_param_model(1.0)
    o = opt.SGD(learning_rate=1.0, parameters=m.parameters(),
                grad_clip=nn.ClipGradByGlobalNorm(0.5))
    w = _step(m, o, 10.0)
    assert abs(w - (1.0 - 0.5)) < 1e-5


def test_amp_gradscaler_flow():
    from paddle_tpu.amp import GradScaler, auto_cast
    m = nn.Linear(4, 4)
    o = opt.SGD(learning_rate=0.01, parameters=m.parameters())
    scaler = GradScaler(init_loss_scaling=1024.0)
    x = paddle.randn([2, 4])
    with auto_cast(True, dtype="bfloat16"):
        out = m(x)
        assert out.dtype == paddle.bfloat16
        loss = out.astype("float32").sum()
    scaler.scale(loss).backward()
    scaler.step(o)
    scaler.update()
    assert scaler.state_dict()["scale"] == 1024.0


def test_cyclic_lr_triangle():
    from paddle_tpu.optimizer.lr import CyclicLR
    s = CyclicLR(base_learning_rate=0.1, max_learning_rate=0.5,
                 step_size_up=4, step_size_down=4)
    lrs = []
    for _ in range(9):
        lrs.append(s())
        s.step()
    assert abs(lrs[0] - 0.1) < 1e-9
    assert abs(lrs[4] - 0.5) < 1e-9   # peak after step_size_up
    assert abs(lrs[8] - 0.1) < 1e-9   # back to base after a full cycle
    # triangular2 halves the second cycle's amplitude
    s2 = CyclicLR(base_learning_rate=0.1, max_learning_rate=0.5,
                  step_size_up=2, step_size_down=2, mode="triangular2")
    seq = []
    for _ in range(7):
        seq.append(s2())
        s2.step()
    assert abs(seq[2] - 0.5) < 1e-9
    assert abs(seq[6] - 0.3) < 1e-9   # base + (0.4)*1*0.5


def test_linear_lr_and_multiplicative():
    from paddle_tpu.optimizer.lr import LinearLR, MultiplicativeDecay
    s = LinearLR(learning_rate=0.2, total_steps=4, start_factor=0.5,
                 end_factor=1.0)
    vals = []
    for _ in range(5):
        vals.append(s())
        s.step()
    assert abs(vals[0] - 0.1) < 1e-9 and abs(vals[4] - 0.2) < 1e-9

    m = MultiplicativeDecay(learning_rate=1.0, lr_lambda=lambda e: 0.5)
    seq = []
    for _ in range(3):
        seq.append(m())
        m.step()
    assert seq == [1.0, 0.5, 0.25]


def test_lars_momentum_adaptive_rate():
    """LARS: layerwise lr scales with ||w||/||g||; a huge-gradient layer
    steps proportionally to the weight norm, not the raw gradient."""
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.optimizer import LarsMomentum

    paddle.seed(0)
    w = paddle.to_tensor(np.ones(4, np.float32)); w.stop_gradient = False
    opt = LarsMomentum(learning_rate=0.1, momentum=0.0, lars_coeff=0.01,
                       lars_weight_decay=0.0, parameters=[w])
    loss = (w * paddle.to_tensor(np.full(4, 1000.0, np.float32))).sum()
    loss.backward()
    w_before = w.numpy().copy()
    opt.step()
    step = w_before - w.numpy()
    # local_lr = 0.1 * 0.01 * ||w|| / ||g||; update = local_lr * g
    wn, gn = np.sqrt(4.0), np.sqrt(4 * 1000.0 ** 2)
    expected = 0.1 * 0.01 * wn / gn * 1000.0
    np.testing.assert_allclose(step, expected, rtol=1e-4)


def test_lars_exclude_from_weight_decay():
    """Excluded params (name substring) get plain momentum: no lars decay,
    no adaptive scaling (reference: BN/bias exclusion)."""
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.optimizer import LarsMomentum

    w = paddle.to_tensor(np.ones(4, np.float32)); w.stop_gradient = False
    w.name = "batch_norm_0.w_0"
    opt = LarsMomentum(learning_rate=0.1, momentum=0.0, lars_coeff=0.01,
                       lars_weight_decay=0.5, parameters=[w],
                       exclude_from_weight_decay=["batch_norm"])
    (w * 2.0).sum().backward()
    before = w.numpy().copy()
    opt.step()
    # plain sgd step: lr * g = 0.1 * 2
    np.testing.assert_allclose(before - w.numpy(), 0.2, rtol=1e-5)
