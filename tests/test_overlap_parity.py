"""Comm–compute overlap parity: the overlapped TP/DP/PP paths must match the
blocking paths BIT-FOR-BIT on the virtual CPU mesh (mp=2, dp=2, pp=2 — the
acceptance bar), with documented fp-tolerance relaxation only for the mp>2
ring all-reduce (it re-associates the partial-sum order; see
parallel/collective_matmul.py docstring)."""
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from paddle_tpu._compat import shard_map
from paddle_tpu.parallel import collective_matmul as cm
from paddle_tpu.parallel.pipeline import (last_stage_value, microbatch,
                                          pipeline_apply, stack_stage_params)

needs_devices = pytest.mark.skipif(
    len(jax.devices("cpu")) < 4, reason="needs >=4 virtual devices")


def _leaves_equal(a, b):
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    return len(la) == len(lb) and all(
        np.array_equal(x, y) for x, y in zip(la, lb))


# ---------------------------------------------------------------------------
# TP: ring collective matmuls vs fused collectives
# ---------------------------------------------------------------------------

def _tp_loss_grads(kernel, mesh, n, in_specs, x, w):
    f = shard_map(lambda a, b: kernel(a, b, n, "mp"), mesh=mesh,
                  in_specs=in_specs, out_specs=P(),
                  axis_names=frozenset(["mp"]), check_vma=False)

    def loss(a, b):
        o = f(a, b)
        return jnp.sum(o * jnp.cos(o)), o

    (l, o), g = jax.jit(
        jax.value_and_grad(loss, argnums=(0, 1), has_aux=True))(x, w)
    return (np.asarray(l), np.asarray(o),
            jax.tree_util.tree_map(np.asarray, g))


@needs_devices
@pytest.mark.parametrize("mp", [2, pytest.param(4, marks=pytest.mark.slow)])
def test_ring_allgather_matmul_bitwise(mp):
    """Column-parallel chunked-pipeline gather: bitwise at ANY degree (no
    cross-rank reduction — every element computed once on its owner)."""
    mesh = Mesh(np.array(jax.devices("cpu")[:mp]), ("mp",))
    rng = np.random.RandomState(0)
    t, k, out = 64, 32, 48 * mp
    x = jnp.asarray(rng.randn(t, k), jnp.float32)
    w = jax.device_put(jnp.asarray(rng.randn(k, out), jnp.float32),
                       NamedSharding(mesh, P(None, "mp")))
    specs = (P(), P(None, "mp"))
    ring = _tp_loss_grads(cm.ring_allgather_matmul, mesh, mp, specs, x, w)
    blk = _tp_loss_grads(cm.blocking_allgather_matmul, mesh, mp, specs, x, w)
    assert _leaves_equal(ring, blk)


@needs_devices
@pytest.mark.parametrize("mp", [2])
def test_ring_allreduce_matmul_bitwise_mp2(mp):
    """Row-parallel reduce-scatter ring: at mp=2 the ring reduction is a
    two-term sum, so forward AND backward are bitwise vs the fused psum."""
    mesh = Mesh(np.array(jax.devices("cpu")[:mp]), ("mp",))
    rng = np.random.RandomState(1)
    t, k, out = 64, 32 * mp, 48
    x = jax.device_put(jnp.asarray(rng.randn(t, k), jnp.float32),
                       NamedSharding(mesh, P(None, "mp")))
    w = jax.device_put(jnp.asarray(rng.randn(k, out), jnp.float32),
                       NamedSharding(mesh, P("mp", None)))
    specs = (P(None, "mp"), P("mp", None))
    ring = _tp_loss_grads(cm.ring_allreduce_matmul, mesh, mp, specs, x, w)
    blk = _tp_loss_grads(cm.blocking_allreduce_matmul, mesh, mp, specs, x, w)
    assert _leaves_equal(ring, blk)


@needs_devices
@pytest.mark.slow
def test_ring_allreduce_matmul_mp4_tolerance():
    """mp>2 re-associates the partial-sum order: fp tolerance, not bitwise."""
    mp = 4
    mesh = Mesh(np.array(jax.devices("cpu")[:mp]), ("mp",))
    rng = np.random.RandomState(2)
    t, k, out = 64, 32 * mp, 48
    x = jax.device_put(jnp.asarray(rng.randn(t, k), jnp.float32),
                       NamedSharding(mesh, P(None, "mp")))
    w = jax.device_put(jnp.asarray(rng.randn(k, out), jnp.float32),
                       NamedSharding(mesh, P("mp", None)))
    specs = (P(None, "mp"), P("mp", None))
    ring = _tp_loss_grads(cm.ring_allreduce_matmul, mesh, mp, specs, x, w)
    blk = _tp_loss_grads(cm.blocking_allreduce_matmul, mesh, mp, specs, x, w)
    # the test loss's cos/sin backward amplifies the reassociation delta by
    # |o| (~30x at these magnitudes); 1e-3 still separates a real schedule
    # bug (the pre-fix wrong ring order was off by ~79 absolute) from fp
    # reassociation noise
    for r, b in zip(jax.tree_util.tree_leaves(ring),
                    jax.tree_util.tree_leaves(blk)):
        np.testing.assert_allclose(r, b, rtol=1e-3, atol=1e-3)


@needs_devices
def test_plan_gates_fall_back_to_fused():
    mesh2 = Mesh(np.array(jax.devices("cpu")[:2]), ("mp",))
    mesh1 = Mesh(np.array(jax.devices("cpu")[:1]), ("mp",))
    os.environ[cm.ENV_MIN_CHUNK] = "16"
    try:
        # viable: chunks >= min_chunk
        assert cm.plan_column_parallel((64, 32), (32, 64), mesh2) is not None
        assert cm.plan_row_parallel((64, 32), (32, 64), mesh2) is not None
        # mp == 1
        assert cm.plan_column_parallel((64, 32), (32, 64), mesh1) is None
        # sub-MXU chunk: 8 cols/shard < min_chunk
        assert cm.plan_column_parallel((64, 32), (32, 16), mesh2) is None
        # indivisible contraction dim
        assert cm.plan_row_parallel((64, 31), (31, 64), mesh2) is None
    finally:
        del os.environ[cm.ENV_MIN_CHUNK]


@needs_devices
def test_tp_overlap_flag_flips_layer_path(monkeypatch):
    """PADDLE_TPU_TP_OVERLAP=1 must route Column/RowParallelLinear through
    the ring kernels (plan non-None); off must keep the fused path."""
    from paddle_tpu.distributed.fleet.meta_parallel.parallel_layers import \
        mp_layers
    mesh = Mesh(np.array(jax.devices("cpu")[:2]).reshape(1, 2), ("dp", "mp"))
    from paddle_tpu.distributed import sharding_utils

    class FakeTensor:
        shape = (4, 16, 32)

    class FakeW:
        shape = (32, 64)

    monkeypatch.setenv(cm.ENV_OVERLAP, "0")
    with sharding_utils.auto_shard(mesh):
        assert mp_layers._overlap_plan("column", FakeTensor, FakeW) is None
    monkeypatch.setenv(cm.ENV_OVERLAP, "1")
    monkeypatch.setenv(cm.ENV_MIN_CHUNK, "4")
    with sharding_utils.auto_shard(mesh):
        assert mp_layers._overlap_plan("column", FakeTensor, FakeW) \
            is not None
        assert mp_layers._overlap_plan("row", FakeTensor, FakeW) is not None
    # no mesh active -> fused
    assert mp_layers._overlap_plan("column", FakeTensor, FakeW) is None


# ---------------------------------------------------------------------------
# DP: explicit/bucketed grad sync vs GSPMD auto
# ---------------------------------------------------------------------------

def _dp_step(grad_sync, bucket_mb=None):
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    from paddle_tpu.jit import TrainStep
    from paddle_tpu.optimizer import AdamW

    paddle.set_device("cpu")
    paddle.seed(11)
    model = nn.Sequential(nn.Linear(16, 32), nn.GELU(), nn.Linear(32, 4))
    opt = AdamW(learning_rate=1e-2, parameters=model.parameters(),
                weight_decay=0.01)
    mesh = Mesh(np.array(jax.devices("cpu")[:2]).reshape(2, 1), ("dp", "mp"))
    step = TrainStep(model, lambda o, l: paddle.mean((o - l) ** 2), opt,
                     mesh=mesh, batch_spec=P("dp"), grad_sync=grad_sync,
                     grad_bucket_mb=bucket_mb)
    rng = np.random.RandomState(3)
    x = paddle.to_tensor(rng.randn(8, 16).astype(np.float32))
    y = paddle.to_tensor(rng.randn(8, 4).astype(np.float32))
    losses = [float(step(x, labels=y)) for _ in range(3)]
    step.sync_to_model()
    params = {k: np.asarray(p._data) for k, p in model.named_parameters()}
    return step, losses, params


@needs_devices
def test_dp_bucketed_equals_explicit_bitwise():
    """Bucketing only changes collective granularity (psum is elementwise):
    bucketed grads == per-param explicit grads bit-for-bit at dp=2."""
    step_e, losses_e, params_e = _dp_step("explicit")
    step_b, losses_b, params_b = _dp_step("bucketed", bucket_mb=0.001)
    assert step_e.grad_sync_mode == "explicit"
    assert step_b.grad_sync_mode == "bucketed"
    assert len(step_b.grad_buckets) > 1  # cap actually split the params
    assert losses_e == losses_b
    assert _leaves_equal(params_e, params_b)


@needs_devices
@pytest.mark.slow
def test_dp_explicit_matches_auto():
    """The explicit island must reproduce the GSPMD auto path numerics."""
    _, losses_a, params_a = _dp_step(None)
    _, losses_e, params_e = _dp_step("explicit")
    np.testing.assert_allclose(losses_e, losses_a, rtol=1e-5)
    for k in params_a:
        np.testing.assert_allclose(params_e[k], params_a[k],
                                   rtol=1e-4, atol=1e-6)


def test_bucket_planning():
    from paddle_tpu.distributed.sharding_utils import plan_grad_buckets
    shapes = {f"p{i}": ((4, 4), 4) for i in range(6)}  # 64B each
    # reverse-topological (grads-ready-first) order, 128B cap -> pairs
    assert plan_grad_buckets(shapes, 128) == [
        ["p5", "p4"], ["p3", "p2"], ["p1", "p0"]]
    # oversized grad gets its own bucket
    shapes["big"] = ((100, 100), 4)
    assert plan_grad_buckets(shapes, 128)[0] == ["big"]


def test_bucket_planning_edge_cases():
    from paddle_tpu.distributed.sharding_utils import (bucket_bytes,
                                                       plan_grad_buckets)
    # a single oversized grad is its own (only) bucket, not dropped
    only_big = {"w": ((1000, 1000), 4)}
    assert plan_grad_buckets(only_big, 128) == [["w"]]
    assert bucket_bytes(only_big, [["w"]]) == [4_000_000]
    # empty shapes dict -> no buckets (and bucket_bytes agrees)
    assert plan_grad_buckets({}, 128) == []
    assert bucket_bytes({}, []) == []
    # reverse=False walks FORWARD (param-creation) order — the stage-3
    # param-gather prefetch planning order
    fwd = {f"p{i}": ((4, 4), 4) for i in range(4)}
    assert plan_grad_buckets(fwd, 128, reverse=False) == [
        ["p0", "p1"], ["p2", "p3"]]
    # zero-dim (scalar) params: 0 dims -> itemsize bytes, packed normally
    scalars = {"s0": ((), 4), "s1": ((), 4), "s2": ((), 4)}
    assert plan_grad_buckets(scalars, 8, reverse=False) == [
        ["s0", "s1"], ["s2"]]
    assert bucket_bytes(scalars, [["s0", "s1"], ["s2"]]) == [8, 4]


# ---------------------------------------------------------------------------
# Chunked per-hop ring tiles (mp>2) + the PR-3 overlap surfaces
# ---------------------------------------------------------------------------

def _tp_loss_grads_chunked(kernel, mesh, n, in_specs, x, w, nchunks):
    import functools
    f = shard_map(functools.partial(kernel, n=n, axis_name="mp",
                                    nchunks=nchunks),
                  mesh=mesh, in_specs=in_specs, out_specs=P(),
                  axis_names=frozenset(["mp"]), check_vma=False)

    def loss(a, b):
        o = f(a, b)
        return jnp.sum(o * jnp.cos(o)), o

    (l, o), g = jax.jit(
        jax.value_and_grad(loss, argnums=(0, 1), has_aux=True))(x, w)
    return (np.asarray(l), np.asarray(o),
            jax.tree_util.tree_map(np.asarray, g))


@needs_devices
@pytest.mark.parametrize("nchunks", [2, 4])
def test_chunked_allreduce_ring_bitwise_vs_unchunked(nchunks):
    """Hop sub-tiling splits transfer granularity only (disjoint row slices
    reassembled by concat): chunked == unchunked BIT-FOR-BIT at mp=4,
    forward and backward."""
    mp = 4
    mesh = Mesh(np.array(jax.devices("cpu")[:mp]), ("mp",))
    rng = np.random.RandomState(4)
    t, k, out = 64, 32 * mp, 48
    x = jax.device_put(jnp.asarray(rng.randn(t, k), jnp.float32),
                       NamedSharding(mesh, P(None, "mp")))
    w = jax.device_put(jnp.asarray(rng.randn(k, out), jnp.float32),
                       NamedSharding(mesh, P("mp", None)))
    specs = (P(None, "mp"), P("mp", None))
    un = _tp_loss_grads_chunked(cm.ring_allreduce_matmul, mesh, mp, specs,
                                x, w, 1)
    ch = _tp_loss_grads_chunked(cm.ring_allreduce_matmul, mesh, mp, specs,
                                x, w, nchunks)
    assert _leaves_equal(un, ch)


@needs_devices
def test_chunked_allgather_ring_bitwise_vs_blocking():
    """The all-gather ring has no cross-rank reduction: chunked stays
    bitwise against the FUSED all-gather at mp=4 (forward and backward)."""
    mp = 4
    mesh = Mesh(np.array(jax.devices("cpu")[:mp]), ("mp",))
    rng = np.random.RandomState(5)
    t, k, out = 64, 32, 48 * mp
    x = jnp.asarray(rng.randn(t, k), jnp.float32)
    w = jax.device_put(jnp.asarray(rng.randn(k, out), jnp.float32),
                       NamedSharding(mesh, P(None, "mp")))
    specs = (P(), P(None, "mp"))
    ch = _tp_loss_grads_chunked(cm.ring_allgather_matmul, mesh, mp, specs,
                                x, w, 4)
    blk = _tp_loss_grads(cm.blocking_allgather_matmul, mesh, mp, specs, x, w)
    assert _leaves_equal(ch, blk)


@needs_devices
def test_mp2_ring_stays_unchunked_and_bitwise():
    """resolve_chunks pins mp<=2 to one tile per hop, and the mp=2 ring
    (the bitwise-vs-blocking contract) is unaffected by the chunk knob."""
    assert cm.resolve_chunks(2, 4096) == 1
    mesh = Mesh(np.array(jax.devices("cpu")[:2]), ("mp",))
    rng = np.random.RandomState(6)
    t, k, out = 64, 64, 48
    x = jax.device_put(jnp.asarray(rng.randn(t, k), jnp.float32),
                       NamedSharding(mesh, P(None, "mp")))
    w = jax.device_put(jnp.asarray(rng.randn(k, out), jnp.float32),
                       NamedSharding(mesh, P("mp", None)))
    specs = (P(None, "mp"), P("mp", None))
    os.environ[cm.ENV_CHUNKS] = "8"
    try:
        ring = _tp_loss_grads(cm.ring_allreduce_matmul, mesh, 2, specs, x, w)
        blk = _tp_loss_grads(cm.blocking_allreduce_matmul, mesh, 2, specs,
                             x, w)
    finally:
        del os.environ[cm.ENV_CHUNKS]
    assert _leaves_equal(ring, blk)


def test_resolve_chunks():
    # auto: ~min_chunk rows per sub-tile, snapped to a divisor
    os.environ[cm.ENV_MIN_CHUNK] = "64"
    try:
        assert cm.resolve_chunks(4, 256) == 4
        assert cm.resolve_chunks(4, 64) == 1
        assert cm.resolve_chunks(8, 96) == 1   # 96//64 -> 1
        assert cm.resolve_chunks(4, 192) == 3  # 192//64=3 divides
    finally:
        del os.environ[cm.ENV_MIN_CHUNK]
    # explicit knob wins when it divides, falls back to 1 when it doesn't
    os.environ[cm.ENV_CHUNKS] = "4"
    try:
        assert cm.resolve_chunks(4, 256) == 4
        assert cm.resolve_chunks(4, 6) == 1
        assert cm.resolve_chunks(2, 256) == 1  # mp=2 always unchunked
    finally:
        del os.environ[cm.ENV_CHUNKS]
    # 'auto'/'' mean auto, not an error
    os.environ[cm.ENV_CHUNKS] = "auto"
    try:
        assert cm.overlap_chunks() is None
    finally:
        del os.environ[cm.ENV_CHUNKS]


@pytest.mark.parametrize("var,fn", [
    (cm.ENV_MIN_CHUNK, cm.min_chunk),
    (cm.ENV_CHUNKS, cm.overlap_chunks),
])
@pytest.mark.parametrize("bad", ["banana", "12.5", "0", "-3"])
def test_env_parsing_rejects_junk(var, fn, bad):
    """Junk or non-positive values raise a ValueError NAMING the variable,
    not an opaque int() traceback."""
    os.environ[var] = bad
    try:
        with pytest.raises(ValueError, match=var):
            fn()
    finally:
        del os.environ[var]


def test_env_parsing_defaults():
    prev_min = os.environ.pop(cm.ENV_MIN_CHUNK, None)
    prev_chunks = os.environ.pop(cm.ENV_CHUNKS, None)
    try:
        assert cm.min_chunk() == 64
        assert cm.overlap_chunks() is None
        os.environ[cm.ENV_MIN_CHUNK] = " 32 "
        assert cm.min_chunk() == 32
    finally:
        os.environ.pop(cm.ENV_MIN_CHUNK, None)
        if prev_min is not None:
            os.environ[cm.ENV_MIN_CHUNK] = prev_min
        if prev_chunks is not None:
            os.environ[cm.ENV_CHUNKS] = prev_chunks


@needs_devices
def test_plans_are_memoized():
    """Same (shapes, mesh, kwargs, overlap env) -> the SAME plan object (no
    island rebuild, no tp.*.plans re-count); changing a knob or shape
    misses."""
    from paddle_tpu.observability import trace as obs
    mesh = Mesh(np.array(jax.devices("cpu")[:2]), ("mp",))
    os.environ[cm.ENV_MIN_CHUNK] = "16"
    try:
        cm.clear_plan_cache()  # noqa: PTA007 -- deliberate cold cache: the test must observe a fresh plan build; later tests replan lazily
        obs.reset_counters()
        p1 = cm.plan_column_parallel((64, 32), (32, 64), mesh)
        p2 = cm.plan_column_parallel((64, 32), (32, 64), mesh)
        assert p1 is not None and p1 is p2
        assert obs.counters().get("tp.column_parallel.plans") == 1
        p3 = cm.plan_column_parallel((128, 32), (32, 64), mesh)
        assert p3 is not None and p3 is not p1
        # env knobs key the cache: flipping MIN_CHUNK must re-plan
        os.environ[cm.ENV_MIN_CHUNK] = "8"
        assert cm.plan_column_parallel((64, 32), (32, 64), mesh) is not p1
        r1 = cm.plan_row_parallel((64, 32), (32, 64), mesh)
        assert r1 is cm.plan_row_parallel((64, 32), (32, 64), mesh)
    finally:
        del os.environ[cm.ENV_MIN_CHUNK]
        cm.clear_plan_cache()


def _fused_ffn_blocking_island(mesh, n, bax=None):
    """Blocking twin of plan_fused_ffn: same island layout, same local
    column matmuls + activation, fused psum instead of the ring."""
    def body(x, w_cols, w_row, b_cols):
        hs = [x @ w for w in w_cols]
        if b_cols:
            hs = [h + b for h, b in zip(hs, b_cols)]
        h = cm.swiglu(*hs)
        return jax.lax.psum(h @ w_row, "mp")
    return shard_map(
        body, mesh=mesh,
        in_specs=(P(bax, None), (P(None, "mp"),) * 2, P("mp", None), ()),
        out_specs=P(bax, None), axis_names=frozenset(mesh.axis_names),
        check_vma=False)


@needs_devices
@pytest.mark.parametrize("mp", [2, pytest.param(4, marks=pytest.mark.slow)])
def test_fused_ffn_parity(mp):
    """Single-island column->swiglu->row vs the blocking twin: bitwise at
    mp=2 (two-term ring sum), fp tolerance at mp=4 (reassociation)."""
    mesh = Mesh(np.array(jax.devices("cpu")[:mp]), ("mp",))
    rng = np.random.RandomState(7)
    t, k, inter = 64, 32, 32 * mp
    os.environ[cm.ENV_OVERLAP] = "1"
    os.environ[cm.ENV_MIN_CHUNK] = "8"
    try:
        cm.clear_plan_cache()  # noqa: PTA007 -- deliberate cold cache: the test must observe a fresh plan build; later tests replan lazily
        plan = cm.plan_fused_ffn((t, k), (k, inter), (inter, k), mesh,
                                 n_cols=2, activation=cm.swiglu,
                                 batch_axis=None)
        assert plan is not None
    finally:
        del os.environ[cm.ENV_OVERLAP]
        del os.environ[cm.ENV_MIN_CHUNK]
    x = jnp.asarray(rng.randn(t, k), jnp.float32)
    wg = jnp.asarray(rng.randn(k, inter) * 0.1, jnp.float32)
    wu = jnp.asarray(rng.randn(k, inter) * 0.1, jnp.float32)
    wd = jnp.asarray(rng.randn(inter, k) * 0.1, jnp.float32)
    blk = _fused_ffn_blocking_island(mesh, mp)

    def l_ring(a, g, u, d):
        o = plan(a, (g, u), d)
        return jnp.sum(o * jnp.cos(o))

    def l_blk(a, g, u, d):
        o = blk(a, (g, u), d, ())
        return jnp.sum(o * jnp.cos(o))

    ring = jax.jit(jax.value_and_grad(l_ring, argnums=(0, 1, 2, 3)))(
        x, wg, wu, wd)
    ref = jax.jit(jax.value_and_grad(l_blk, argnums=(0, 1, 2, 3)))(
        x, wg, wu, wd)
    if mp == 2:
        assert _leaves_equal(ring, ref)
    else:
        for r, b in zip(jax.tree_util.tree_leaves(ring),
                        jax.tree_util.tree_leaves(ref)):
            np.testing.assert_allclose(r, b, rtol=1e-3, atol=1e-4)


@needs_devices
def test_vocab_embed_ring_exact():
    """Masked local lookup + reduce ring: every row is non-zero on exactly
    one vocab shard, so the ring sum is EXACT (forward bitwise vs dense
    lookup; table grads match the dense scatter-add)."""
    mp = 4
    mesh = Mesh(np.array(jax.devices("cpu")[:mp]), ("mp",))
    rng = np.random.RandomState(8)
    V, H, B, S = 32, 16, 4, 16
    os.environ[cm.ENV_OVERLAP] = "1"
    os.environ[cm.ENV_MIN_CHUNK] = "8"
    try:
        cm.clear_plan_cache()  # noqa: PTA007 -- deliberate cold cache: the test must observe a fresh plan build; later tests replan lazily
        plan = cm.plan_vocab_parallel_embedding((B, S), (V, H), mesh,
                                                batch_axis=None)
        assert plan is not None
    finally:
        del os.environ[cm.ENV_OVERLAP]
        del os.environ[cm.ENV_MIN_CHUNK]
    tab = jnp.asarray(rng.randn(V, H), jnp.float32)
    ids = jnp.asarray(rng.randint(0, V, (B, S)), jnp.int32)
    out = jax.jit(lambda i, w: plan(i, w))(ids, tab)
    assert np.array_equal(np.asarray(out), np.asarray(tab)[np.asarray(ids)])
    g_ring = jax.jit(jax.grad(lambda w: jnp.sum(jnp.sin(plan(ids, w)))))(tab)
    g_ref = jax.jit(jax.grad(lambda w: jnp.sum(jnp.sin(w[ids]))))(tab)
    np.testing.assert_allclose(np.asarray(g_ring), np.asarray(g_ref),
                               rtol=1e-6, atol=1e-6)


@needs_devices
def test_parallel_ce_ring_parity():
    """Ring-gathered (max, sumexp, picked) stats vs the replicated-logits
    logsumexp: fp tolerance (the log-sum is re-associated); the picked
    logit lives on one rank so its gathered sum is exact."""
    mp = 4
    mesh = Mesh(np.array(jax.devices("cpu")[:mp]), ("mp",))
    rng = np.random.RandomState(9)
    B, S, V = 4, 8, 64
    os.environ[cm.ENV_OVERLAP] = "1"
    os.environ[cm.ENV_MIN_CHUNK] = "8"
    try:
        cm.clear_plan_cache()  # noqa: PTA007 -- deliberate cold cache: the test must observe a fresh plan build; later tests replan lazily
        plan = cm.plan_parallel_cross_entropy((B, S, V), mesh,
                                              batch_axis=None)
        assert plan is not None
    finally:
        del os.environ[cm.ENV_OVERLAP]
        del os.environ[cm.ENV_MIN_CHUNK]
    logits = jnp.asarray(rng.randn(B, S, V), jnp.float32)
    lbl = jnp.asarray(rng.randint(0, V, (B, S)), jnp.int32)

    def ref(lg):
        l32 = lg.astype(jnp.float32)
        lse = jax.scipy.special.logsumexp(l32, axis=-1)
        return lse - jnp.take_along_axis(l32, lbl[..., None], -1)[..., 0]

    loss = jax.jit(lambda lg: plan(lg, lbl))(logits)
    np.testing.assert_allclose(np.asarray(loss), np.asarray(ref(logits)),
                               rtol=1e-5, atol=1e-6)
    g1 = jax.jit(jax.grad(lambda lg: jnp.sum(plan(lg, lbl))))(logits)
    g2 = jax.jit(jax.grad(lambda lg: jnp.sum(ref(lg))))(logits)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2),
                               rtol=1e-5, atol=1e-6)


def _gpt2_mlp_losses(overlap):
    """Train a lone GPT2MLP through TrainStep at mp=2 (the same harness the
    fleet parity tests use) with the fused-FFN island on or off."""
    import paddle_tpu as paddle
    from paddle_tpu.jit import TrainStep
    from paddle_tpu.models.gpt2 import GPT2Config, GPT2MLP
    from paddle_tpu.optimizer import AdamW

    paddle.set_device("cpu")
    if overlap:
        os.environ[cm.ENV_OVERLAP] = "1"
        os.environ[cm.ENV_MIN_CHUNK] = "8"
    cm.clear_plan_cache()
    try:
        paddle.seed(13)
        cfg = GPT2Config(vocab_size=64, hidden_size=32, num_layers=1,
                         num_heads=2, max_position=32, intermediate_size=64,
                         dropout=0.0)
        model = GPT2MLP(cfg)
        opt = AdamW(learning_rate=1e-2, parameters=model.parameters())
        mesh = Mesh(np.array(jax.devices("cpu")[:2]).reshape(1, 2),
                    ("dp", "mp"))
        step = TrainStep(model, lambda o, l: paddle.mean((o - l) ** 2), opt,
                         mesh=mesh, batch_spec=P("dp"))
        rng = np.random.RandomState(10)
        x = paddle.to_tensor(rng.randn(4, 16, 32).astype(np.float32))
        y = paddle.to_tensor(rng.randn(4, 16, 32).astype(np.float32))
        return [float(step(x, labels=y)) for _ in range(3)]
    finally:
        if overlap:
            del os.environ[cm.ENV_OVERLAP]
            del os.environ[cm.ENV_MIN_CHUNK]
        cm.clear_plan_cache()


@needs_devices
def test_gpt2_mlp_fused_overlap_matches_blocking():
    """GPT2MLP trained through TrainStep must produce the same losses with
    the fused-FFN island on vs off at mp=2 (bitwise ring degree; only fp
    noise from GSPMD partitioning differences is tolerated)."""
    base = _gpt2_mlp_losses(False)
    fused = _gpt2_mlp_losses(True)
    np.testing.assert_allclose(fused, base, rtol=2e-6, atol=1e-7)


def _sp_ffn_losses(overlap):
    """Column->gelu->Row SP pair through fused_sequence_parallel_ffn, fused
    island on (overlap env) or the layer-by-layer fallback."""
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    from paddle_tpu.distributed.fleet.meta_parallel import (
        ColumnParallelLinear, RowParallelLinear)
    from paddle_tpu.distributed.fleet.utils.sequence_parallel_utils import \
        fused_sequence_parallel_ffn
    from paddle_tpu.jit import TrainStep
    from paddle_tpu.optimizer import AdamW

    class SPBlock(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc_in = ColumnParallelLinear(32, 64, gather_output=False)
            self.fc_out = RowParallelLinear(64, 32, input_is_parallel=True)

        def forward(self, x):
            return fused_sequence_parallel_ffn(self.fc_in, self.fc_out, x)

    paddle.set_device("cpu")
    if overlap:
        os.environ[cm.ENV_OVERLAP] = "1"
        os.environ[cm.ENV_MIN_CHUNK] = "8"
    cm.clear_plan_cache()
    try:
        paddle.seed(17)
        model = SPBlock()
        opt = AdamW(learning_rate=1e-2, parameters=model.parameters())
        mesh = Mesh(np.array(jax.devices("cpu")[:2]).reshape(1, 2),
                    ("dp", "mp"))
        step = TrainStep(model, lambda o, l: paddle.mean((o - l) ** 2), opt,
                         mesh=mesh, batch_spec=P("dp"))
        rng = np.random.RandomState(18)
        x = paddle.to_tensor(rng.randn(4, 16, 32).astype(np.float32))
        y = paddle.to_tensor(rng.randn(4, 16, 32).astype(np.float32))
        return [float(step(x, labels=y)) for _ in range(3)]
    finally:
        if overlap:
            del os.environ[cm.ENV_OVERLAP]
            del os.environ[cm.ENV_MIN_CHUNK]
        cm.clear_plan_cache()


@needs_devices
def test_sequence_parallel_fused_ffn_matches_fallback():
    """fused_sequence_parallel_ffn: the single-island route must match the
    layer-by-layer SP fallback at mp=2."""
    base = _sp_ffn_losses(False)
    fused = _sp_ffn_losses(True)
    np.testing.assert_allclose(fused, base, rtol=2e-6, atol=1e-7)


# ---------------------------------------------------------------------------
# PP: async-p2p schedule vs blocking schedule
# ---------------------------------------------------------------------------

def _pp_loss_grads(S, M, overlap):
    H = 16
    mesh = Mesh(np.array(jax.devices("cpu")[:S]), ("pp",))
    rng = np.random.RandomState(0)
    per_stage = [{"w": jnp.asarray(rng.randn(H, H), jnp.float32) * 0.3,
                  "b": jnp.asarray(rng.randn(H), jnp.float32) * 0.1}
                 for _ in range(S)]
    stacked = stack_stage_params(per_stage)
    x_mb = microbatch(jnp.asarray(rng.randn(M * 2, H), jnp.float32), M)
    pipe = pipeline_apply(lambda p, h: jnp.tanh(h @ p["w"] + p["b"]),
                          S, M, "pp", remat=True, overlap_p2p=overlap)

    def island(params, xm):
        loss = jnp.sum(pipe(params, xm) ** 2)
        return last_stage_value(loss, S, "pp")

    f = shard_map(island, mesh=mesh, in_specs=(P("pp"), P()), out_specs=P(),
                  axis_names=frozenset(["pp"]), check_vma=False)
    loss, grads = jax.jit(
        jax.value_and_grad(lambda p: f(p, x_mb)))(stacked)
    return np.asarray(loss), jax.tree_util.tree_map(np.asarray, grads)


@needs_devices
@pytest.mark.parametrize("S,M", [(2, 4),
                                 pytest.param(4, 4, marks=pytest.mark.slow)])
def test_pp_overlap_bitwise(S, M):
    """The double-buffered schedule applies identical per-microbatch ops
    (one extra skew tick, same stage math): loss AND grads bitwise."""
    blk = _pp_loss_grads(S, M, overlap=False)
    ovl = _pp_loss_grads(S, M, overlap=True)
    assert np.array_equal(blk[0], ovl[0])
    assert _leaves_equal(blk[1], ovl[1])


@needs_devices
@pytest.mark.slow
def test_pp_overlap_via_llama_config():
    """overlap_p2p plumbs through ParallelConfig into the pp train step."""
    from paddle_tpu.models.llama import (ParallelConfig, build_train_step,
                                         llama_tiny, make_mesh)
    from paddle_tpu.ops import _common
    losses = {}
    with _common.interpret_mode(True):
        for ovl in (False, True):
            parallel = ParallelConfig(dp=1, pp=2, microbatches=4,
                                      use_flash=False, overlap_p2p=ovl)
            config = llama_tiny(vocab=64, hidden=32, layers=4, heads=4,
                                kv_heads=4, inter=64, seq=32)
            mesh = make_mesh(parallel, devices=jax.devices("cpu")[:2])
            step, params, opt = build_train_step(config, parallel, mesh=mesh,
                                                 lr=1e-3)
            rng = np.random.RandomState(0)
            ids = rng.randint(0, 64, (4, 32)).astype(np.int32)
            labels = np.roll(ids, -1, 1).astype(np.int32)
            _, _, loss = step(params, opt, ids, labels)
            losses[ovl] = float(jax.device_get(loss))
    assert losses[True] == losses[False]
