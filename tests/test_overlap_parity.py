"""Comm–compute overlap parity: the overlapped TP/DP/PP paths must match the
blocking paths BIT-FOR-BIT on the virtual CPU mesh (mp=2, dp=2, pp=2 — the
acceptance bar), with documented fp-tolerance relaxation only for the mp>2
ring all-reduce (it re-associates the partial-sum order; see
parallel/collective_matmul.py docstring)."""
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from paddle_tpu._compat import shard_map
from paddle_tpu.parallel import collective_matmul as cm
from paddle_tpu.parallel.pipeline import (last_stage_value, microbatch,
                                          pipeline_apply, stack_stage_params)

needs_devices = pytest.mark.skipif(
    len(jax.devices("cpu")) < 4, reason="needs >=4 virtual devices")


def _leaves_equal(a, b):
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    return len(la) == len(lb) and all(
        np.array_equal(x, y) for x, y in zip(la, lb))


# ---------------------------------------------------------------------------
# TP: ring collective matmuls vs fused collectives
# ---------------------------------------------------------------------------

def _tp_loss_grads(kernel, mesh, n, in_specs, x, w):
    f = shard_map(lambda a, b: kernel(a, b, n, "mp"), mesh=mesh,
                  in_specs=in_specs, out_specs=P(),
                  axis_names=frozenset(["mp"]), check_vma=False)

    def loss(a, b):
        o = f(a, b)
        return jnp.sum(o * jnp.cos(o)), o

    (l, o), g = jax.jit(
        jax.value_and_grad(loss, argnums=(0, 1), has_aux=True))(x, w)
    return (np.asarray(l), np.asarray(o),
            jax.tree_util.tree_map(np.asarray, g))


@needs_devices
@pytest.mark.parametrize("mp", [2, pytest.param(4, marks=pytest.mark.slow)])
def test_ring_allgather_matmul_bitwise(mp):
    """Column-parallel chunked-pipeline gather: bitwise at ANY degree (no
    cross-rank reduction — every element computed once on its owner)."""
    mesh = Mesh(np.array(jax.devices("cpu")[:mp]), ("mp",))
    rng = np.random.RandomState(0)
    t, k, out = 64, 32, 48 * mp
    x = jnp.asarray(rng.randn(t, k), jnp.float32)
    w = jax.device_put(jnp.asarray(rng.randn(k, out), jnp.float32),
                       NamedSharding(mesh, P(None, "mp")))
    specs = (P(), P(None, "mp"))
    ring = _tp_loss_grads(cm.ring_allgather_matmul, mesh, mp, specs, x, w)
    blk = _tp_loss_grads(cm.blocking_allgather_matmul, mesh, mp, specs, x, w)
    assert _leaves_equal(ring, blk)


@needs_devices
@pytest.mark.parametrize("mp", [2])
def test_ring_allreduce_matmul_bitwise_mp2(mp):
    """Row-parallel reduce-scatter ring: at mp=2 the ring reduction is a
    two-term sum, so forward AND backward are bitwise vs the fused psum."""
    mesh = Mesh(np.array(jax.devices("cpu")[:mp]), ("mp",))
    rng = np.random.RandomState(1)
    t, k, out = 64, 32 * mp, 48
    x = jax.device_put(jnp.asarray(rng.randn(t, k), jnp.float32),
                       NamedSharding(mesh, P(None, "mp")))
    w = jax.device_put(jnp.asarray(rng.randn(k, out), jnp.float32),
                       NamedSharding(mesh, P("mp", None)))
    specs = (P(None, "mp"), P("mp", None))
    ring = _tp_loss_grads(cm.ring_allreduce_matmul, mesh, mp, specs, x, w)
    blk = _tp_loss_grads(cm.blocking_allreduce_matmul, mesh, mp, specs, x, w)
    assert _leaves_equal(ring, blk)


@needs_devices
@pytest.mark.slow
def test_ring_allreduce_matmul_mp4_tolerance():
    """mp>2 re-associates the partial-sum order: fp tolerance, not bitwise."""
    mp = 4
    mesh = Mesh(np.array(jax.devices("cpu")[:mp]), ("mp",))
    rng = np.random.RandomState(2)
    t, k, out = 64, 32 * mp, 48
    x = jax.device_put(jnp.asarray(rng.randn(t, k), jnp.float32),
                       NamedSharding(mesh, P(None, "mp")))
    w = jax.device_put(jnp.asarray(rng.randn(k, out), jnp.float32),
                       NamedSharding(mesh, P("mp", None)))
    specs = (P(None, "mp"), P("mp", None))
    ring = _tp_loss_grads(cm.ring_allreduce_matmul, mesh, mp, specs, x, w)
    blk = _tp_loss_grads(cm.blocking_allreduce_matmul, mesh, mp, specs, x, w)
    # the test loss's cos/sin backward amplifies the reassociation delta by
    # |o| (~30x at these magnitudes); 1e-3 still separates a real schedule
    # bug (the pre-fix wrong ring order was off by ~79 absolute) from fp
    # reassociation noise
    for r, b in zip(jax.tree_util.tree_leaves(ring),
                    jax.tree_util.tree_leaves(blk)):
        np.testing.assert_allclose(r, b, rtol=1e-3, atol=1e-3)


@needs_devices
def test_plan_gates_fall_back_to_fused():
    mesh2 = Mesh(np.array(jax.devices("cpu")[:2]), ("mp",))
    mesh1 = Mesh(np.array(jax.devices("cpu")[:1]), ("mp",))
    os.environ[cm.ENV_MIN_CHUNK] = "16"
    try:
        # viable: chunks >= min_chunk
        assert cm.plan_column_parallel((64, 32), (32, 64), mesh2) is not None
        assert cm.plan_row_parallel((64, 32), (32, 64), mesh2) is not None
        # mp == 1
        assert cm.plan_column_parallel((64, 32), (32, 64), mesh1) is None
        # sub-MXU chunk: 8 cols/shard < min_chunk
        assert cm.plan_column_parallel((64, 32), (32, 16), mesh2) is None
        # indivisible contraction dim
        assert cm.plan_row_parallel((64, 31), (31, 64), mesh2) is None
    finally:
        del os.environ[cm.ENV_MIN_CHUNK]


@needs_devices
def test_tp_overlap_flag_flips_layer_path(monkeypatch):
    """PADDLE_TPU_TP_OVERLAP=1 must route Column/RowParallelLinear through
    the ring kernels (plan non-None); off must keep the fused path."""
    from paddle_tpu.distributed.fleet.meta_parallel.parallel_layers import \
        mp_layers
    mesh = Mesh(np.array(jax.devices("cpu")[:2]).reshape(1, 2), ("dp", "mp"))
    from paddle_tpu.distributed import sharding_utils

    class FakeTensor:
        shape = (4, 16, 32)

    class FakeW:
        shape = (32, 64)

    monkeypatch.setenv(cm.ENV_OVERLAP, "0")
    with sharding_utils.auto_shard(mesh):
        assert mp_layers._overlap_plan("column", FakeTensor, FakeW) is None
    monkeypatch.setenv(cm.ENV_OVERLAP, "1")
    monkeypatch.setenv(cm.ENV_MIN_CHUNK, "4")
    with sharding_utils.auto_shard(mesh):
        assert mp_layers._overlap_plan("column", FakeTensor, FakeW) \
            is not None
        assert mp_layers._overlap_plan("row", FakeTensor, FakeW) is not None
    # no mesh active -> fused
    assert mp_layers._overlap_plan("column", FakeTensor, FakeW) is None


# ---------------------------------------------------------------------------
# DP: explicit/bucketed grad sync vs GSPMD auto
# ---------------------------------------------------------------------------

def _dp_step(grad_sync, bucket_mb=None):
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    from paddle_tpu.jit import TrainStep
    from paddle_tpu.optimizer import AdamW

    paddle.set_device("cpu")
    paddle.seed(11)
    model = nn.Sequential(nn.Linear(16, 32), nn.GELU(), nn.Linear(32, 4))
    opt = AdamW(learning_rate=1e-2, parameters=model.parameters(),
                weight_decay=0.01)
    mesh = Mesh(np.array(jax.devices("cpu")[:2]).reshape(2, 1), ("dp", "mp"))
    step = TrainStep(model, lambda o, l: paddle.mean((o - l) ** 2), opt,
                     mesh=mesh, batch_spec=P("dp"), grad_sync=grad_sync,
                     grad_bucket_mb=bucket_mb)
    rng = np.random.RandomState(3)
    x = paddle.to_tensor(rng.randn(8, 16).astype(np.float32))
    y = paddle.to_tensor(rng.randn(8, 4).astype(np.float32))
    losses = [float(step(x, labels=y)) for _ in range(3)]
    step.sync_to_model()
    params = {k: np.asarray(p._data) for k, p in model.named_parameters()}
    return step, losses, params


@needs_devices
def test_dp_bucketed_equals_explicit_bitwise():
    """Bucketing only changes collective granularity (psum is elementwise):
    bucketed grads == per-param explicit grads bit-for-bit at dp=2."""
    step_e, losses_e, params_e = _dp_step("explicit")
    step_b, losses_b, params_b = _dp_step("bucketed", bucket_mb=0.001)
    assert step_e.grad_sync_mode == "explicit"
    assert step_b.grad_sync_mode == "bucketed"
    assert len(step_b.grad_buckets) > 1  # cap actually split the params
    assert losses_e == losses_b
    assert _leaves_equal(params_e, params_b)


@needs_devices
@pytest.mark.slow
def test_dp_explicit_matches_auto():
    """The explicit island must reproduce the GSPMD auto path numerics."""
    _, losses_a, params_a = _dp_step(None)
    _, losses_e, params_e = _dp_step("explicit")
    np.testing.assert_allclose(losses_e, losses_a, rtol=1e-5)
    for k in params_a:
        np.testing.assert_allclose(params_e[k], params_a[k],
                                   rtol=1e-4, atol=1e-6)


def test_bucket_planning():
    from paddle_tpu.distributed.sharding_utils import plan_grad_buckets
    shapes = {f"p{i}": ((4, 4), 4) for i in range(6)}  # 64B each
    # reverse-topological (grads-ready-first) order, 128B cap -> pairs
    assert plan_grad_buckets(shapes, 128) == [
        ["p5", "p4"], ["p3", "p2"], ["p1", "p0"]]
    # oversized grad gets its own bucket
    shapes["big"] = ((100, 100), 4)
    assert plan_grad_buckets(shapes, 128)[0] == ["big"]


# ---------------------------------------------------------------------------
# PP: async-p2p schedule vs blocking schedule
# ---------------------------------------------------------------------------

def _pp_loss_grads(S, M, overlap):
    H = 16
    mesh = Mesh(np.array(jax.devices("cpu")[:S]), ("pp",))
    rng = np.random.RandomState(0)
    per_stage = [{"w": jnp.asarray(rng.randn(H, H), jnp.float32) * 0.3,
                  "b": jnp.asarray(rng.randn(H), jnp.float32) * 0.1}
                 for _ in range(S)]
    stacked = stack_stage_params(per_stage)
    x_mb = microbatch(jnp.asarray(rng.randn(M * 2, H), jnp.float32), M)
    pipe = pipeline_apply(lambda p, h: jnp.tanh(h @ p["w"] + p["b"]),
                          S, M, "pp", remat=True, overlap_p2p=overlap)

    def island(params, xm):
        loss = jnp.sum(pipe(params, xm) ** 2)
        return last_stage_value(loss, S, "pp")

    f = shard_map(island, mesh=mesh, in_specs=(P("pp"), P()), out_specs=P(),
                  axis_names=frozenset(["pp"]), check_vma=False)
    loss, grads = jax.jit(
        jax.value_and_grad(lambda p: f(p, x_mb)))(stacked)
    return np.asarray(loss), jax.tree_util.tree_map(np.asarray, grads)


@needs_devices
@pytest.mark.parametrize("S,M", [(2, 4),
                                 pytest.param(4, 4, marks=pytest.mark.slow)])
def test_pp_overlap_bitwise(S, M):
    """The double-buffered schedule applies identical per-microbatch ops
    (one extra skew tick, same stage math): loss AND grads bitwise."""
    blk = _pp_loss_grads(S, M, overlap=False)
    ovl = _pp_loss_grads(S, M, overlap=True)
    assert np.array_equal(blk[0], ovl[0])
    assert _leaves_equal(blk[1], ovl[1])


@needs_devices
@pytest.mark.slow
def test_pp_overlap_via_llama_config():
    """overlap_p2p plumbs through ParallelConfig into the pp train step."""
    from paddle_tpu.models.llama import (ParallelConfig, build_train_step,
                                         llama_tiny, make_mesh)
    from paddle_tpu.ops import _common
    _common.set_interpret(True)
    losses = {}
    for ovl in (False, True):
        parallel = ParallelConfig(dp=1, pp=2, microbatches=4,
                                  use_flash=False, overlap_p2p=ovl)
        config = llama_tiny(vocab=64, hidden=32, layers=4, heads=4,
                            kv_heads=4, inter=64, seq=32)
        mesh = make_mesh(parallel, devices=jax.devices("cpu")[:2])
        step, params, opt = build_train_step(config, parallel, mesh=mesh,
                                             lr=1e-3)
        rng = np.random.RandomState(0)
        ids = rng.randint(0, 64, (4, 32)).astype(np.int32)
        labels = np.roll(ids, -1, 1).astype(np.int32)
        _, _, loss = step(params, opt, ids, labels)
        losses[ovl] = float(jax.device_get(loss))
    _common.set_interpret(None)
    assert losses[True] == losses[False]
