"""Paged decode attention (ops/paged_attention.py): numerics contract.

The kernel walks per-sequence block tables over a shared block pool on a
flat scalar-prefetched schedule (the grouped_matmul tile_schedule idiom:
dead steps replay the last live step so their DMAs are elided). The
contract pinned here (PARITY.md "Paged-attention numerics"):

  * B=1, one live block: BITWISE equal to decode_attention_slab on the
    contiguous layout (the acceptance pin — both kernels run the exact
    same op sequence per tile).
  * fragmented table == contiguous table, bitwise, at any block count
    (gathering through the table is pure data movement).
  * the fused attend+update kernel matches decode_attend_update_slab
    bitwise on outputs AND on the cache contents it writes, including a
    new token that straddles into a fresh block.
  * multi-sequence ragged batches match the XLA reference to f32
    accumulation tolerance.

Everything runs in pallas interpret mode on CPU with tiny shapes.
"""
import numpy as np
import pytest

import jax.numpy as jnp

from paddle_tpu.ops import _common
from paddle_tpu.ops.decode_attention import (decode_attend_update_slab,
                                             decode_attention_slab)
from paddle_tpu.ops.paged_attention import (_LOG2E, paged_attend_update,
                                            paged_attention,
                                            paged_attention_xla,
                                            paged_schedule,
                                            paged_schedule_stats)

L, NH, HD, BS = 2, 4, 32, 128
KVD = NH * HD


@pytest.fixture(autouse=True)
def _interpret():
    with _common.interpret_mode(True):
        yield


@pytest.fixture(scope="module")
def data():
    rng = np.random.RandomState(0)
    q = rng.randn(1, NH, KVD).astype(np.float32) * 0.1
    qs = jnp.asarray(q * (_LOG2E / (HD ** 0.5)))
    pool_k = rng.randn(L, 4, KVD, BS).astype(np.float32)
    pool_v = rng.randn(L, 4, KVD, BS).astype(np.float32)
    return qs, jnp.asarray(pool_k), jnp.asarray(pool_v), pool_k, pool_v


def test_single_block_bitwise_vs_slab(data):
    """Acceptance pin: contiguous single-block layout is BITWISE equal to
    the slab decode kernel (block_size == the slab's 128-lane T tile)."""
    qs, kp, vp, pool_k, pool_v = data
    out = paged_attention(qs, kp, vp, jnp.asarray([[1]], jnp.int32),
                          jnp.asarray([70], jnp.int32), 1)
    out_slab = decode_attention_slab(qs, jnp.asarray(pool_k[:, 1:2]),
                                     jnp.asarray(pool_v[:, 1:2]), 1, 69)
    assert (np.asarray(out) == np.asarray(out_slab)).all()


def test_fragmented_table_bitwise_vs_contiguous_slab(data):
    """Three blocks in non-monotone pool order == the same tokens laid out
    contiguously, bitwise — table indirection is pure data movement."""
    qs, kp, vp, pool_k, pool_v = data
    out = paged_attention(qs, kp, vp, jnp.asarray([[2, 0, 3]], jnp.int32),
                          jnp.asarray([300], jnp.int32), 0)
    kc = np.concatenate([pool_k[:, 2:3], pool_k[:, 0:1], pool_k[:, 3:4]], -1)
    vc = np.concatenate([pool_v[:, 2:3], pool_v[:, 0:1], pool_v[:, 3:4]], -1)
    out_slab = decode_attention_slab(qs, jnp.asarray(kc), jnp.asarray(vc),
                                     0, 299)
    assert (np.asarray(out) == np.asarray(out_slab)).all()


def test_multi_seq_ragged_vs_xla_reference():
    """Ragged batch (lengths 129/384/17, unequal block counts, padded table
    slots pointing at the null block) vs the dense XLA reference."""
    rng = np.random.RandomState(1)
    q = rng.randn(3, NH, KVD).astype(np.float32) * 0.1
    qs = jnp.asarray(q * (_LOG2E / (HD ** 0.5)))
    kp = jnp.asarray(rng.randn(L, 8, KVD, BS).astype(np.float32))
    vp = jnp.asarray(rng.randn(L, 8, KVD, BS).astype(np.float32))
    tables = jnp.asarray([[5, 2, 0], [1, 3, 7], [4, 0, 0]], jnp.int32)
    lens = jnp.asarray([129, 384, 17], jnp.int32)
    out = paged_attention(qs, kp, vp, tables, lens, 1)
    ref = paged_attention_xla(jnp.asarray(q), kp, vp, tables, lens, 1,
                              1.0 / (HD ** 0.5))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_fused_update_bitwise_and_cache_contents(data):
    """attend+update == slab attend+update bitwise, on the attention output
    AND the merged cache tile it writes back through the aliased outs."""
    qs, kp, vp, pool_k, pool_v = data
    rng = np.random.RandomState(2)
    newk = rng.randn(1, KVD).astype(np.float32)
    newv = rng.randn(1, KVD).astype(np.float32)
    tables = jnp.asarray([[1, 3]], jnp.int32)
    out, kp_u, vp_u = paged_attend_update(
        qs, jnp.asarray(newk), jnp.asarray(newv), kp, vp, tables,
        jnp.asarray([127], jnp.int32), 1)
    kc = np.concatenate([pool_k[:, 1:2], pool_k[:, 3:4]], -1)
    vc = np.concatenate([pool_v[:, 1:2], pool_v[:, 3:4]], -1)
    out_s, kcs, vcs = decode_attend_update_slab(
        qs, jnp.asarray(newk), jnp.asarray(newv),
        jnp.asarray(kc), jnp.asarray(vc), 1, 127)
    assert (np.asarray(out) == np.asarray(out_s)).all()
    assert (np.asarray(kp_u)[1, 1] == np.asarray(kcs)[1, 0, :, :BS]).all()
    assert (np.asarray(vp_u)[1, 1] == np.asarray(vcs)[1, 0, :, :BS]).all()


def test_fused_update_straddles_into_fresh_block(data):
    """New token at pos == block_size lands in column 0 of the NEXT table
    slot; output and written block still match the slab path bitwise."""
    qs, kp, vp, pool_k, pool_v = data
    rng = np.random.RandomState(3)
    newk = rng.randn(1, KVD).astype(np.float32)
    newv = rng.randn(1, KVD).astype(np.float32)
    tables = jnp.asarray([[1, 3]], jnp.int32)
    out, kp_u, vp_u = paged_attend_update(
        qs, jnp.asarray(newk), jnp.asarray(newv), kp, vp, tables,
        jnp.asarray([BS], jnp.int32), 1)
    kc = np.concatenate([pool_k[:, 1:2], pool_k[:, 3:4]], -1)
    vc = np.concatenate([pool_v[:, 1:2], pool_v[:, 3:4]], -1)
    out_s, kcs, _ = decode_attend_update_slab(
        qs, jnp.asarray(newk), jnp.asarray(newv),
        jnp.asarray(kc), jnp.asarray(vc), 1, BS)
    assert (np.asarray(out) == np.asarray(out_s)).all()
    kb3 = np.asarray(kp_u)[1, 3]
    assert (kb3[:, 0] == newk[0]).all()
    assert (kb3 == np.asarray(kcs)[1, 0, :, BS:]).all()


def test_schedule_dead_steps_replay_last_live():
    """Flat-schedule invariant: steps past the live total re-present the
    last live (seq, block) pair so Mosaic elides their DMAs, and per-seq
    boundaries carry first/last flags exactly once per sequence."""
    tables = np.asarray([[5, 2, 0], [1, 3, 7], [4, 0, 0]], np.int32)
    lens = np.asarray([129, 384, 17], np.int32)
    sched = np.asarray(paged_schedule(jnp.asarray(lens),
                                      jnp.asarray(tables), 9, BS))
    seq, blk, start, first, last, live = sched[:6]
    assert live.tolist() == [1, 1, 1, 1, 1, 1, 0, 0, 0]
    # live walk: seq0 blocks [5,2], seq1 [1,3,7], seq2 [4]; dead replays
    assert seq.tolist() == [0, 0, 1, 1, 1, 2, 2, 2, 2]
    assert blk.tolist() == [5, 2, 1, 3, 7, 4, 4, 4, 4]
    assert first.tolist() == [1, 0, 1, 0, 0, 1, 0, 0, 0]
    assert last.tolist() == [0, 1, 0, 0, 1, 1, 0, 0, 0]
    stats = paged_schedule_stats(lens, tables, 9, BS)
    assert stats["live_steps"] == 6 and stats["dead_steps"] == 3


if __name__ == "__main__":
    pytest.main([__file__, "-q"])
