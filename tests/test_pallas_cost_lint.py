"""Thin shim over ``paddle_tpu.analysis`` rule PTA003 (the lint's logic
moved there): every ``pl.pallas_call(...)`` site in ``paddle_tpu/ops/``
must pass ``cost_estimate=`` so XLA's cost model sees kernel FLOPs. A
custom call without one is costed at ZERO, which silently deflates the
StepMetrics MFU attribution for every kernel-backed step."""
import pytest

from paddle_tpu.analysis import Module, run
from paddle_tpu.analysis.rules.pta003_cost_estimate import (
    MIN_SITES, CostEstimateRule)


def test_every_pallas_call_passes_cost_estimate():
    # with_floors keeps the >= MIN_SITES coverage floor: a finalize()
    # finding fires if the AST walk ever stops seeing the kernel
    # population, exactly as the pre-migration lint asserted
    report = run(rules=["PTA003"], with_floors=True)
    assert not report.active, \
        "\n".join(f.format() for f in report.active)


def test_coverage_floor_is_at_least_the_premigration_bar():
    # flash fwd/bwd, varlen fwd/bwd (streaming + stacked + fused +
    # split), decode slab x2, rms_norm, paged attention read + fused
    # update: the ops package holds >= 12 kernel sites
    assert MIN_SITES >= 12


def test_lint_catches_a_missing_cost_estimate():
    """The rule itself must flag a bare pallas_call (guard against the
    AST walk silently matching nothing)."""
    mod = Module.from_source("pl.pallas_call(kernel, grid=(4,))(x)\n",
                             rel="paddle_tpu/ops/_synthetic.py")
    rule = CostEstimateRule(root=".")
    findings = list(rule.check_module(mod))
    assert len(findings) == 1
    assert findings[0].rule == "PTA003"
    assert "cost_estimate" in findings[0].message


if __name__ == "__main__":
    pytest.main([__file__, "-q"])
