"""Static lint: every ``pl.pallas_call(...)`` site in ``paddle_tpu/ops/``
must pass ``cost_estimate=`` so XLA's cost model sees kernel FLOPs. A
custom call without one is costed at ZERO, which silently deflates the
StepMetrics MFU attribution for every kernel-backed step (observability).
Pattern follows tests/test_comm_span_lint.py."""
import ast
import os

import pytest

OPS = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                   "paddle_tpu", "ops")


def _pallas_calls(tree):
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        name = fn.id if isinstance(fn, ast.Name) else (
            fn.attr if isinstance(fn, ast.Attribute) else None)
        if name == "pallas_call":
            yield node


def _py_files():
    for root, _dirs, files in os.walk(OPS):
        for f in files:
            if f.endswith(".py"):
                yield os.path.join(root, f)


def test_every_pallas_call_passes_cost_estimate():
    offenders = []
    seen = 0
    for path in _py_files():
        with open(path) as fh:
            src = fh.read()
        if "pallas_call" not in src:
            continue
        tree = ast.parse(src, filename=path)
        for call in _pallas_calls(tree):
            seen += 1
            if not any(kw.arg == "cost_estimate" for kw in call.keywords):
                offenders.append(f"{os.path.relpath(path, OPS)}:"
                                 f"{call.lineno}")
    # flash fwd/bwd, varlen fwd/bwd (streaming + stacked + fused + split),
    # decode slab x2, rms_norm, paged attention read + fused update: the
    # ops package holds >= 12 kernel sites
    assert seen >= 12, f"lint found only {seen} pallas_call sites"
    assert not offenders, (
        "pallas_call sites missing cost_estimate=: " + ", ".join(offenders))


def test_lint_catches_a_missing_cost_estimate():
    """The lint itself must flag a bare pallas_call (guard against the AST
    walk silently matching nothing)."""
    tree = ast.parse("pl.pallas_call(kernel, grid=(4,))(x)\n")
    calls = list(_pallas_calls(tree))
    assert len(calls) == 1
    assert not any(kw.arg == "cost_estimate" for kw in calls[0].keywords)


if __name__ == "__main__":
    pytest.main([__file__, "-q"])
