"""Regression-ratchet tests: baseline round-trip, band semantics (worse
beyond band fails, improvements pass WITHOUT moving the baseline), the
--accept-only baseline move, torn/stale detection, the direction/band
heuristics, the one detail->rungs mapping, and the CLI exit codes."""
import json
import os

import pytest

from paddle_tpu.observability import regress as rg


def _write(path, doc):
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(doc, fh)
    return str(path)


def _seed(tmp_path, rungs, **kw):
    base = str(tmp_path / "baseline.json")
    rg.write_baseline(rungs, path=base, **kw)
    return base


# -- direction / band heuristics ---------------------------------------------

def test_direction_heuristics():
    assert rg.direction("llama_train_mfu") == "higher"
    assert rg.direction("serve_tpot_p99_s") == "lower"
    assert rg.direction("ledger_overhead_pct") == "lower"
    assert rg.direction("ledger_unattributed_frac") == "lower"
    assert rg.direction("decode_flagship_b8_x_floor") == "lower"
    assert rg.direction("serve_kv_int8_decode_ms_ratio") == "lower"
    # an actual bool value pins the gate regardless of name
    assert rg.direction("serve_tpot_p99_s", value=True) == "bool"


def test_default_band_widens_noisy_rungs():
    assert rg.default_band("serve_tokens_per_sec", 0.15) == 0.5
    assert rg.default_band("serve_tpot_p99_s", 0.15) == 0.5
    assert rg.default_band("ledger_unattributed_frac", 0.15) == 0.15
    assert rg.default_band("7b_mfu", 0.15) == 0.15
    # an operator-widened default is never narrowed
    assert rg.default_band("serve_tokens_per_sec", 0.8) == 0.8


def test_band_env_knob(monkeypatch):
    monkeypatch.delenv(rg.ENV_REGRESS_BAND, raising=False)
    assert rg.band_default() == 0.15
    monkeypatch.setenv(rg.ENV_REGRESS_BAND, "0.25")
    assert rg.band_default() == 0.25


# -- baseline I/O -------------------------------------------------------------

def test_baseline_round_trip(tmp_path):
    rungs = {"7b_mfu": 0.41, "serve_tpot_p99_s": 0.02,
             "ledger_clean": True, "skipped": None}
    base = _seed(tmp_path, rungs, band=0.15)
    data = rg.load_baseline(base)
    e = data["entries"]
    assert set(e) == {"7b_mfu", "serve_tpot_p99_s", "ledger_clean"}
    assert e["7b_mfu"] == {"value": 0.41, "direction": "higher",
                           "band": 0.15}
    assert e["serve_tpot_p99_s"]["direction"] == "lower"
    assert e["serve_tpot_p99_s"]["band"] == 0.5  # noisy timing rung
    # bool gates carry no band
    assert e["ledger_clean"] == {"value": True, "direction": "bool"}


def test_write_baseline_preserves_operator_band_and_direction(tmp_path):
    base = _seed(tmp_path, {"7b_mfu": 0.41}, band=0.15)
    prev = rg.load_baseline(base)
    prev["entries"]["7b_mfu"]["band"] = 0.33  # operator-tuned
    rg.write_baseline({"7b_mfu": 0.44, "new_rung": 1.0}, path=base,
                      band=0.15, prev=prev)
    e = rg.load_baseline(base)["entries"]
    assert e["7b_mfu"] == {"value": 0.44, "direction": "higher",
                           "band": 0.33}
    assert e["new_rung"]["band"] == 0.15


def test_load_baseline_missing_is_empty(tmp_path):
    assert rg.load_baseline(str(tmp_path / "nope.json")) == {}


@pytest.mark.parametrize("doc, defect", [
    ("{not json", "unparseable"),
    ({"entries": [1, 2]}, "no 'entries'"),
    ({"entries": {"r": {"direction": "higher"}}}, "no value"),
    ({"entries": {"r": {"value": 1.0}}}, "no direction"),
    ({"entries": {"r": {"value": 1.0, "direction": "sideways"}}},
     "no direction"),
])
def test_torn_baseline_named(tmp_path, doc, defect):
    path = tmp_path / "torn.json"
    if isinstance(doc, str):
        path.write_text(doc)
    else:
        _write(path, doc)
    with pytest.raises(rg.TornBaseline, match=defect):
        rg.load_baseline(str(path))


# -- check semantics ----------------------------------------------------------

def test_regression_beyond_band_fails_within_band_passes(tmp_path):
    base = rg.load_baseline(_seed(tmp_path, {"7b_mfu": 0.40}, band=0.10))
    ok = rg.check({"7b_mfu": 0.37}, base)          # -7.5%: inside band
    assert ok["ok"] and ok["unchanged"] == ["7b_mfu"]
    bad = rg.check({"7b_mfu": 0.30}, base)         # -25%: beyond band
    assert not bad["ok"] and bad["regressed"] == ["7b_mfu"]


def test_lower_is_better_band_is_one_sided(tmp_path):
    base = rg.load_baseline(_seed(
        tmp_path, {"ledger_overhead_pct": 1.0}, band=0.10))
    assert rg.check({"ledger_overhead_pct": 1.05}, base)["ok"]
    res = rg.check({"ledger_overhead_pct": 1.5}, base)
    assert not res["ok"] and res["regressed"] == ["ledger_overhead_pct"]


def test_improvement_passes_without_moving_baseline(tmp_path):
    path = _seed(tmp_path, {"7b_mfu": 0.40}, band=0.10)
    before = open(path).read()
    res = rg.check({"7b_mfu": 0.55}, rg.load_baseline(path))
    assert res["ok"] and res["improved"] == ["7b_mfu"]
    assert any("baseline unmoved" in l for l in res["lines"])
    assert open(path).read() == before  # a lucky run can't raise the bar


def test_bool_gate_regression_and_repair(tmp_path):
    base = rg.load_baseline(_seed(tmp_path, {"clean": True,
                                             "was_broken": False}))
    res = rg.check({"clean": False, "was_broken": True}, base)
    # true->false regresses; false->true is an improvement, not a trip
    assert res["regressed"] == ["clean"]
    assert res["improved"] == ["was_broken"]


def test_stale_entry_fails_new_rung_does_not(tmp_path):
    base = rg.load_baseline(_seed(tmp_path, {"7b_mfu": 0.40}, band=0.10))
    res = rg.check({"fresh_rung": 9.0}, base)
    assert not res["ok"]
    assert res["stale"] == ["7b_mfu"] and res["new"] == ["fresh_rung"]
    assert any("lost guard" in l for l in res["lines"])
    ok = rg.check({"7b_mfu": 0.40, "fresh_rung": 9.0}, base)
    assert ok["ok"] and ok["new"] == ["fresh_rung"]


# -- the one detail->rungs mapping --------------------------------------------

def test_rungs_from_bench_detail_ledger_section():
    doc = {"metric": "llama_train_mfu", "value": 0.42,
           "detail": {"ledger_roofline": {
               "unattributed_frac": 0.31, "ledger_overhead_pct": 0.12,
               "ledger_losses_identical": True, "steps": 8}}}
    rungs = rg.rungs_from_bench_detail(doc)
    assert rungs["llama_train_mfu"] == 0.42
    assert rungs["ledger_unattributed_frac"] == 0.31
    assert rungs["ledger_overhead_pct"] == 0.12
    assert rungs["ledger_clean"] is True


def test_rungs_from_summary_line_shape():
    doc = {"metric": "llama_train_mfu", "value": 0.42,
           "rungs": {"7b_mfu": 0.4}}
    assert rg.rungs_from_bench_detail(doc) == {"llama_train_mfu": 0.42,
                                               "7b_mfu": 0.4}


def test_load_record_flat_mapping(tmp_path):
    path = _write(tmp_path / "flat.json", {"7b_mfu": 0.4})
    assert rg.load_record(path) == {"7b_mfu": 0.4}


# -- CLI ----------------------------------------------------------------------

def test_cli_accept_then_check_then_injected_regression(tmp_path, capsys):
    rec = _write(tmp_path / "rec.json", {"7b_mfu": 0.40,
                                         "ledger_clean": True})
    base = str(tmp_path / "baseline.json")
    # no baseline yet: --check refuses, --accept is the only seed path
    assert rg.main(["--check", "--record", rec, "--baseline", base]) == 1
    assert rg.main(["--accept", "--record", rec, "--baseline", base]) == 0
    assert rg.main(["--check", "--record", rec, "--baseline", base]) == 0
    out = capsys.readouterr().out
    assert "PASS" in out
    # an injected synthetic regression trips the gate
    bad = _write(tmp_path / "bad.json", {"7b_mfu": 0.10,
                                         "ledger_clean": False})
    assert rg.main(["--check", "--record", bad, "--baseline", base]) == 1
    assert "FAIL" in capsys.readouterr().out
    # --accept (and only --accept) moves the baseline down
    assert rg.main(["--accept", "--record", bad, "--baseline", base]) == 0
    assert rg.main(["--check", "--record", bad, "--baseline", base]) == 0


def test_cli_unreadable_record_exit_2(tmp_path):
    assert rg.main(["--check", "--record", str(tmp_path / "nope.json"),
                    "--baseline", str(tmp_path / "b.json")]) == 2


def test_cli_torn_baseline_exit_1(tmp_path, capsys):
    rec = _write(tmp_path / "rec.json", {"7b_mfu": 0.4})
    torn = tmp_path / "torn.json"
    torn.write_text("{not json")
    assert rg.main(["--check", "--record", rec,
                    "--baseline", str(torn)]) == 1
    assert "TORN" in capsys.readouterr().err
    # --accept repairs a torn baseline
    assert rg.main(["--accept", "--record", rec,
                    "--baseline", str(torn)]) == 0
    assert rg.main(["--check", "--record", rec,
                    "--baseline", str(torn)]) == 0


def test_checked_in_baseline_is_loadable_and_covers_ledger_rungs():
    data = rg.load_baseline()  # repo PERF_BASELINE.json; raises if torn
    entries = data["entries"]
    assert {"ledger_unattributed_frac", "ledger_overhead_pct",
            "ledger_clean"} <= set(entries)
    assert entries["ledger_clean"]["direction"] == "bool"
    assert entries["ledger_overhead_pct"]["direction"] == "lower"
