"""Interleaved (virtual-stage) collective pipeline vs dense ground truth."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

import paddle_tpu  # noqa: F401
from paddle_tpu.parallel.pipeline import pipeline_apply_interleave

S, V, M, H = 4, 2, 8, 16


@pytest.fixture(scope="module")
def mesh():
    return Mesh(np.array(jax.devices("cpu")[:S]), ("pp",))


def _chunks(seed=0):
    rng = np.random.RandomState(seed)
    return [jnp.asarray(rng.randn(H, H).astype(np.float32) * 0.3)
            for _ in range(S * V)]


def _chunk_fn(w, h):
    return jnp.tanh(h @ w)


def _stack_round_robin(ws):
    """Device s gets slots [v]: chunk v*S+s (Megatron layout)."""
    rows = []
    for s in range(S):
        for v in range(V):
            rows.append(ws[v * S + s])
    return jnp.stack(rows)


def _run(mesh, stacked, x):
    pipe = pipeline_apply_interleave(_chunk_fn, S, V, M)
    def collect(params, xmb):
        out = pipe(params, xmb)
        return jax.lax.psum(out, "pp")  # only the last stage writes
    return shard_map(collect, mesh=mesh, in_specs=(P("pp"), P()),
                     out_specs=P(), check_rep=False)(stacked, x)


def test_interleave_matches_dense(mesh):
    ws = _chunks()
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(M, 4, H).astype(np.float32))
    ref = x
    for w in ws:
        ref = jnp.tanh(ref @ w)
    out = _run(mesh, _stack_round_robin(ws), x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_interleave_grads_flow(mesh):
    ws = _chunks(2)
    rng = np.random.RandomState(3)
    x = jnp.asarray(rng.randn(M, 4, H).astype(np.float32))
    stacked = _stack_round_robin(ws)

    def loss(params):
        return (_run(mesh, params, x) ** 2).sum()

    def dense_loss(flat):
        h = x
        for v in range(V):          # chunk order: v*S+s -> rows are s*V+v
            pass
        # rebuild chunk order from the round-robin stack
        ordered = [flat[s * V + v] for v in range(V) for s in range(S)]
        for w in ordered:
            h = jnp.tanh(h @ w)
        return (h ** 2).sum()

    g_pipe = jax.grad(loss)(stacked)
    g_ref = jax.grad(dense_loss)(stacked)
    np.testing.assert_allclose(np.asarray(g_pipe), np.asarray(g_ref),
                               atol=2e-4, rtol=1e-3)


def test_pipeline_layer_virtual_segmentation():
    import paddle_tpu.nn as nn
    from paddle_tpu.distributed.fleet.meta_parallel.parallel_layers.pp_layers \
        import LayerDesc, PipelineLayer

    descs = [LayerDesc(nn.Linear, 8, 8) for _ in range(8)]
    pl = PipelineLayer(descs, num_stages=2, num_virtual_pipeline_stages=2)
    chunks = pl.get_model_chunks()
    assert len(chunks) == 4 and all(len(c) == 2 for c in chunks)
    # stage 0 hosts chunks 0 and 2 (round-robin)
    mine = pl.get_model_chunks(0)
    assert mine[0] == chunks[0] and mine[1] == chunks[2]
    assert pl._stage_layers[0] == [chunks[0], chunks[2]]
