"""Prefix-cached serving: COW shared KV blocks (PR 16).

Contracts pinned here (PARITY.md "Prefix cache semantics"):

  * BlockPool hardening: ``free()`` on a block with refs > 1
    decrements; a double-decrement raises BlockPoolError BEFORE
    mutating anything; the leak audit (``used_blocks``) counts a
    shared block once and a parked cache block zero times.
  * COW invariants: a scheduler write into a block with other readers
    copies it first (readers keep the old bytes); a write into a
    registered ref-1 block invalidates the index entry instead.
  * cached-vs-cold parity: a prefix hit produces BITWISE identical
    greedy tokens to the cold prefill of the same prompt.
  * eviction under pressure reclaims only unreferenced (parked) cache
    blocks, LRU-oldest first — caching never steals live capacity.
  * sharpened admission: an identical-prompt burst admits MORE
    requests with the cache on than off at the same pool size.
  * deterministic replay is unchanged by caching (same trace ->
    identical events and tokens).

Tiny model, pallas interpret mode on CPU.
"""
import numpy as np
import pytest

import jax.numpy as jnp

from paddle_tpu.inference import (BlockPool, BlockPoolError, InferenceEngine,
                                  PrefixCache, Request, ServeConfig)
import paddle_tpu.inference.engine as engine_mod
from paddle_tpu.models.llama import (greedy_generate, init_llama_params,
                                     llama_tiny)
from paddle_tpu.ops import _common


@pytest.fixture(autouse=True)
def _interpret():
    with _common.interpret_mode(True):
        yield


# -- BlockPool ref counts + cached parking -----------------------------------


def test_shared_free_decrements_then_releases():
    pool = BlockPool(6, 128)
    (b,) = pool.alloc(1)
    pool.acquire([b])                       # second reader
    assert pool.ref_count(b) == 2
    assert pool.used_blocks == 1            # shared counts ONCE
    pool.free([b])
    assert pool.ref_count(b) == 1           # decrement, not release
    assert pool.used_blocks == 1
    pool.free([b])
    assert pool.ref_count(b) == 0
    assert pool.used_blocks == 0 and pool.free_blocks == 5


def test_double_free_raises_before_mutating():
    pool = BlockPool(6, 128)
    a, b = pool.alloc(2)
    pool.free([a])
    snapshot = (pool.free_blocks, pool.ref_count(b))
    with pytest.raises(BlockPoolError, match="double free"):
        pool.free([a])                      # stale id
    with pytest.raises(BlockPoolError, match="double free"):
        pool.free([b, b])                   # duplicate WITHIN one call
    # the rejected frees left the pool untouched
    assert (pool.free_blocks, pool.ref_count(b)) == snapshot
    pool.free([b])
    assert pool.used_blocks == 0


def test_free_validates_null_and_range():
    pool = BlockPool(4, 128)
    with pytest.raises(BlockPoolError, match="null"):
        pool.free([0])
    with pytest.raises(BlockPoolError, match="out-of-range"):
        pool.free([4])
    with pytest.raises(BlockPoolError):
        pool.acquire([9])


def test_cached_parking_and_lru_reclaim():
    """Registered blocks park on last free; alloc drains the true free
    list FIRST, then reclaims parked blocks oldest-first with the
    reclaim callback."""
    pool = BlockPool(5, 128)                # 4 usable
    reclaimed = []
    pool.reclaim_cb = reclaimed.append
    a, b = pool.alloc(2)
    pool.mark_cached(a)
    pool.mark_cached(b)
    pool.free([a])                          # parks (LRU-oldest)
    pool.free([b])                          # parks (MRU)
    assert pool.cached_blocks == 2 and pool.used_blocks == 0
    assert pool.free_blocks == 2 and pool.available_blocks == 4
    got = pool.alloc(3)                     # 2 free + 1 reclaim
    assert len(got) == 3
    assert reclaimed == [a]                 # LRU-oldest reclaimed first
    assert not pool.is_registered(a)
    assert pool.is_registered(b) and pool.cached_blocks == 1
    pool.free(got)
    assert pool.used_blocks == 0


def test_acquire_revives_parked_block():
    pool = BlockPool(4, 128)
    (b,) = pool.alloc(1)
    pool.mark_cached(b)
    pool.free([b])
    assert pool.cached_blocks == 1
    pool.acquire([b])                       # prefix hit
    assert pool.ref_count(b) == 1 and pool.cached_blocks == 0
    assert pool.is_registered(b)            # still index-backed
    pool.free([b])                          # parks again
    assert pool.cached_blocks == 1
    pool.unmark_cached(b)                   # index invalidation
    assert pool.free_blocks == 3 and pool.cached_blocks == 0


# -- PrefixCache index --------------------------------------------------------


def test_prefix_cache_register_match_exact_tokens():
    pool = BlockPool(8, 128)
    cache = PrefixCache(pool)
    toks = list(range(1, 300))              # 2 full blocks + tail
    blocks = pool.alloc(3)
    assert cache.register(toks, blocks, 2) == 2
    assert cache.match(toks, 2) == blocks[:2]
    # one differing token inside block 0 -> no hit (exact tuples,
    # no hash collisions by construction)
    other = list(toks)
    other[5] += 1
    assert cache.match(other, 2) == []
    # shorter prefix that shares block 0 hits exactly one block
    assert cache.match(toks[:200], 1) == blocks[:1]
    st = cache.stats()
    assert st["entries"] == 2 and st["hits"] == 2 and st["lookups"] == 3


def test_prefix_cache_first_writer_wins():
    pool = BlockPool(8, 128)
    cache = PrefixCache(pool)
    toks = list(range(1, 200))
    first = pool.alloc(1)
    second = pool.alloc(1)
    assert cache.register(toks, first, 1) == 1
    assert cache.register(toks, second, 1) == 0   # duplicate key skipped
    assert cache.match(toks, 1) == first
    assert not pool.is_registered(second[0])


def test_prefix_cache_reclaim_drops_entry():
    pool = BlockPool(3, 128)                # 2 usable
    cache = PrefixCache(pool)
    toks = list(range(1, 150))
    blocks = pool.alloc(1)
    cache.register(toks, blocks, 1)
    pool.free(blocks)                       # parks
    got = pool.alloc(2)                     # must reclaim the parked block
    assert set(got) >= set(blocks)
    assert cache.match(toks, 1) == []       # entry died with the block
    assert cache.stats()["reclaimed"] == 1
    pool.free(got)


# -- engine: COW, parity, eviction, admission, replay -------------------------


@pytest.fixture(scope="module")
def model():
    cfg = llama_tiny(vocab=96, hidden=64, layers=1, heads=4, kv_heads=2,
                     seq=512)
    return cfg, init_llama_params(cfg, seed=3)


def _serve(**kw):
    base = dict(block_size=128, num_blocks=12, max_batch=2,
                prefill_chunk=64, max_seq_len=512)
    base.update(kw)
    return ServeConfig(**base)


def _run(model, reqs, **kw):
    cfg, params = model
    eng = InferenceEngine(params, cfg, _serve(**kw), record_events=True)
    eng.run([Request(list(p), max_new_tokens=m, arrival=a)
             for p, m, a in reqs], deterministic=True)
    return eng, {s.req.request_id: s.generated for s in eng.finished}


def test_cached_hit_bitwise_equals_cold(model):
    """The tentpole parity pin: request 1 re-sends request 0's prompt
    after registration; it HITS (2 full blocks skipped) and its greedy
    tokens are bitwise identical to the cold run AND to the contiguous
    greedy_generate reference."""
    cfg, params = model
    rng = np.random.RandomState(0)
    prompt = rng.randint(1, 96, size=300).tolist()
    trace = [(prompt, 4, 0.0), (prompt, 4, 50.0)]
    eng_cold, cold = _run(model, trace)
    eng_warm, warm = _run(model, trace, prefix_cache=True)
    pc = eng_warm.stats()["prefix_cache"]
    assert pc["hits"] == 1 and pc["hit_tokens"] == 256
    assert warm == cold
    ref = greedy_generate(params, jnp.asarray([prompt], jnp.int32), cfg, 4)
    assert warm[1] == np.asarray(ref)[0].tolist()
    assert any(e[1:] == ("prefix_hit", 1, 2) for e in eng_warm.events)
    # no leaks; registered blocks sit parked, not lost
    assert eng_warm.pool.used_blocks == 0
    assert eng_warm.pool.cached_blocks == pc["entries"] > 0


def test_cow_copy_preserves_reader_bytes(model):
    """Drive _cow_span directly on a genuinely shared block: the writer
    gets a private copy (table swap), the other reader's block keeps
    its exact bytes, and the copy starts bitwise identical."""
    cfg, params = model
    rng = np.random.RandomState(1)
    prompt = rng.randint(1, 96, size=300).tolist()
    eng, _ = _run(model, [(prompt, 3, 0.0)], prefix_cache=True)
    hit = eng.cache.match(prompt, 2)
    assert len(hit) == 2
    eng.pool.acquire(hit)                   # reader A
    eng.pool.acquire(hit)                   # reader B
    b = hit[0]
    assert eng.pool.ref_count(b) == 2
    before = np.asarray(eng.k_pool[:, b]).copy()
    writer = engine_mod._Seq(Request(prompt, max_new_tokens=1,
                                     request_id=99), 0.0)
    writer.blocks = list(hit)
    assert eng._cow_span(writer, 0, 1)      # write lands in block 0
    nb = writer.blocks[0]
    assert nb != b                          # writer swapped to a copy
    assert eng.pool.ref_count(b) == 1       # reader count decremented
    assert (np.asarray(eng.k_pool[:, b]) == before).all()
    assert (np.asarray(eng.k_pool[:, nb]) == before).all()
    assert eng.stats()["prefix_cache"]["cow_copies"] == 1
    eng.pool.free(writer.blocks)
    eng.pool.free(hit[1:])
    eng.pool.free([b])


def test_cow_sole_owner_invalidates_index_entry(model):
    """ref-1 + registered: no copy, but the index forgets the entry so
    future lookups can't hit mutated bytes."""
    cfg, params = model
    rng = np.random.RandomState(2)
    prompt = rng.randint(1, 96, size=200).tolist()
    eng, _ = _run(model, [(prompt, 3, 0.0)], prefix_cache=True)
    hit = eng.cache.match(prompt, 1)
    assert len(hit) == 1
    eng.pool.acquire(hit)                   # sole live owner
    writer = engine_mod._Seq(Request(prompt, max_new_tokens=1,
                                     request_id=98), 0.0)
    writer.blocks = list(hit)
    assert eng._cow_span(writer, 0, 1)
    assert writer.blocks == hit             # no copy made
    assert not eng.pool.is_registered(hit[0])
    assert eng.cache.match(prompt, 1) == []
    assert eng.stats()["prefix_cache"]["invalidated"] == 1
    eng.pool.free(hit)


def test_eviction_reclaims_only_unreferenced_cache_blocks(model):
    """Pool sized so later admissions must reclaim parked cache blocks:
    the run completes leak-free, reclaims happened, and every stream
    still matches its cold reference (live shared bytes were never
    stolen)."""
    rng = np.random.RandomState(3)
    prompts = [rng.randint(1, 96, size=260).tolist() for _ in range(4)]
    trace = [(p, 3, float(10 * i)) for i, p in enumerate(prompts)]
    eng_cold, cold = _run(model, trace, num_blocks=8)
    eng, warm = _run(model, trace, num_blocks=8, prefix_cache=True)
    assert warm == cold
    assert eng.pool.used_blocks == 0
    pc = eng.stats()["prefix_cache"]
    assert pc["reclaimed"] > 0              # pressure actually reclaimed
    # whatever remains parked is still coherent with the index
    assert eng.pool.cached_blocks == pc["entries"]


def test_burst_admission_admits_more_with_cache(model):
    """Satellite pin: at the same pool size and overcommit, a burst of
    identical prompts admits MORE requests with the cache on — shared
    prefix blocks are free-by-construction in the demand estimate."""
    cfg, params = model

    def admitted(prefix_cache):
        serve = _serve(num_blocks=5, overcommit=1.0, max_queue=16,
                       prefix_cache=prefix_cache)
        eng = InferenceEngine(params, cfg, serve)
        rng = np.random.RandomState(4)
        prompt = rng.randint(1, 96, size=300).tolist()
        outs = [eng.submit(Request(list(prompt), max_new_tokens=4,
                                   arrival=0.0))
                for _ in range(4)]
        assert all(a.cause in (None, "overcommit") for a in outs)
        return sum(a.accepted for a in outs)

    n_off, n_on = admitted(False), admitted(True)
    assert n_on > n_off, (n_on, n_off)


def test_deterministic_replay_with_cache(model):
    """Same arrival trace twice with caching on: identical event logs
    and identical tokens (the cache introduces no nondeterminism)."""
    rng = np.random.RandomState(5)
    shared = rng.randint(1, 96, size=280).tolist()
    other = rng.randint(1, 96, size=40).tolist()
    trace = [(shared, 3, 0.0), (other, 3, 1.0), (shared, 3, 40.0)]
    eng1, t1 = _run(model, trace, prefix_cache=True)
    eng2, t2 = _run(model, trace, prefix_cache=True)
    assert eng1.events == eng2.events
    assert t1 == t2
    assert eng1.stats()["prefix_cache"]["hits"] >= 1


def test_env_knob_enables_prefix_cache(model, monkeypatch):
    cfg, params = model
    monkeypatch.setenv("PADDLE_TPU_SERVE_PREFIX_CACHE", "1")
    eng = InferenceEngine(params, cfg, _serve())
    assert eng.cache is not None
    # explicit config wins over the knob
    eng2 = InferenceEngine(params, cfg, _serve(prefix_cache=False))
    assert eng2.cache is None
