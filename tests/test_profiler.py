"""Profiler tests: scheduler edge cases, single-fire on_trace_ready,
chrome-trace export paths/naming, summary time units, nested RecordEvent."""
import json
import os

import pytest

from paddle_tpu import profiler
from paddle_tpu.profiler import (Profiler, ProfilerState, RecordEvent,
                                 export_chrome_tracing, make_scheduler)


# -- make_scheduler edge cases ----------------------------------------------

def test_scheduler_basic_cycle():
    sched = make_scheduler(closed=1, ready=1, record=2, repeat=0)
    states = [sched(i) for i in range(8)]
    assert states[:4] == [ProfilerState.CLOSED, ProfilerState.READY,
                          ProfilerState.RECORD,
                          ProfilerState.RECORD_AND_RETURN]
    assert states[4:] == states[:4]  # repeat=0 cycles forever


def test_scheduler_skip_first():
    sched = make_scheduler(closed=0, ready=1, record=1, skip_first=3)
    assert [sched(i) for i in range(3)] == [ProfilerState.CLOSED] * 3
    assert sched(3) == ProfilerState.READY
    assert sched(4) == ProfilerState.RECORD_AND_RETURN


def test_scheduler_repeat_exhausts():
    sched = make_scheduler(closed=1, ready=0, record=1, repeat=2)
    assert sched(1) == ProfilerState.RECORD_AND_RETURN
    assert sched(3) == ProfilerState.RECORD_AND_RETURN
    # after `repeat` cycles the scheduler pins CLOSED
    assert all(sched(i) == ProfilerState.CLOSED for i in range(4, 10))


def test_scheduler_record_one_is_record_and_return():
    # a 1-step record window must close itself (RECORD_AND_RETURN), or the
    # window would never export
    sched = make_scheduler(closed=2, ready=1, record=1)
    assert sched(3) == ProfilerState.RECORD_AND_RETURN
    assert sched(2) == ProfilerState.READY


# -- single-fire on_trace_ready ---------------------------------------------

def _run(prof, n):
    prof.start()
    for _ in range(n):
        with RecordEvent("tick"):
            pass
        prof.step()
    prof.stop()


def test_on_trace_ready_fires_once_per_window():
    fired = []
    prof = Profiler(scheduler=make_scheduler(closed=1, ready=1, record=2,
                                             repeat=1),
                    on_trace_ready=lambda p: fired.append(p._step),
                    timer_only=True)
    _run(prof, 6)
    # window closes once at the RECORD_AND_RETURN->CLOSED edge (step 4);
    # stop() must NOT re-fire for the already-exported window
    assert fired == [4]


def test_stop_fires_pending_window_once():
    fired = []
    prof = Profiler(on_trace_ready=lambda p: fired.append(1),
                    timer_only=True)
    prof.start()
    with RecordEvent("w"):
        pass
    prof.stop()
    prof.stop()  # double stop: still exactly one export
    assert fired == [1]


def test_back_to_back_windows_fire_separately():
    fired = []
    prof = Profiler(scheduler=make_scheduler(closed=0, ready=1, record=1,
                                             repeat=2),
                    on_trace_ready=lambda p: fired.append(p._step),
                    timer_only=True)
    _run(prof, 4)
    assert len(fired) == 2


# -- export_chrome_tracing (satellite a) -------------------------------------

def test_export_chrome_tracing_writes_into_dir(tmp_path):
    out = str(tmp_path / "prof_out")
    prof = Profiler(scheduler=make_scheduler(closed=0, ready=1, record=1,
                                             repeat=1),
                    on_trace_ready=export_chrome_tracing(out, "workerA"),
                    timer_only=True)
    _run(prof, 2)
    files = os.listdir(out)
    assert len(files) == 1
    assert files[0].startswith("workerA_time_")
    assert files[0].endswith(".paddle_trace.json")
    data = json.load(open(os.path.join(out, files[0])))
    assert "traceEvents" in data


def test_export_chrome_tracing_default_worker_name(tmp_path):
    out = str(tmp_path / "prof_out2")
    prof = Profiler(on_trace_ready=export_chrome_tracing(out),
                    timer_only=True)
    prof.start()
    with RecordEvent("span"):
        pass
    prof.stop()
    (name,) = os.listdir(out)
    assert name.startswith("host_") and f"pid_{os.getpid()}" in name


# -- nested RecordEvent -> chrome trace (satellite d) -------------------------

def test_nested_record_events_chrome_json(tmp_path):
    prof = Profiler(timer_only=True)
    prof.start()
    with RecordEvent("outer"):
        with RecordEvent("inner"):
            pass
        with RecordEvent("inner"):
            pass
    # overlapping begin/end via explicit API
    a = RecordEvent("manual")
    a.begin()
    a.end()
    prof.stop()
    path = str(tmp_path / "trace.json")
    prof.export(path)
    events = json.load(open(path))["traceEvents"]
    names = [e["name"] for e in events]
    assert names.count("inner") >= 2
    assert "outer" in names and "manual" in names
    outer = next(e for e in events if e["name"] == "outer")
    inners = [e for e in events if e["name"] == "inner"]
    for e in events:
        assert e["ph"] == "X" and e["dur"] >= 0
    # nesting: both inner spans lie inside the outer span
    for i in inners:
        assert outer["ts"] <= i["ts"]
        assert i["ts"] + i["dur"] <= outer["ts"] + outer["dur"] + 1e-3


# -- summary time units (satellite c) ----------------------------------------

def test_summary_time_units():
    prof = Profiler(timer_only=True)
    prof.start()
    with RecordEvent("unit_span"):
        sum(range(10000))
    prof.stop()
    s_ms = prof.summary(time_unit="ms")
    assert "Total(ms)" in s_ms and "unit_span" in s_ms

    def total(report):
        line = next(l for l in report.splitlines() if "unit_span" in l)
        return float(line.split()[-1])

    t_s = total(prof.summary(time_unit="s"))
    t_ms = total(prof.summary(time_unit="ms"))
    t_us = total(prof.summary(time_unit="us"))
    # report renders 3 decimals: a sub-ms span prints 0.000 in seconds, so
    # only ms<->us are exactly comparable; s must still parse and be smaller
    assert t_ms > 0 and t_s <= t_ms
    assert t_us == pytest.approx(t_ms * 1e3, abs=0.5)  # 3-decimal rounding
    with pytest.raises(ValueError):
        prof.summary(time_unit="fortnights")


def test_summary_includes_telemetry_section():
    from paddle_tpu import observability as obs
    m = obs.StepMetrics(name="sumtest", peak_flops=1e12)
    m.record_compile(compile_s=0.1, flops=1e6)
    m.step()
    m.step()
    obs.set_active(m)
    try:
        prof = Profiler(timer_only=True)
        prof.start()
        with RecordEvent("x"):
            pass
        prof.stop()
        assert "StepMetrics[sumtest]" in prof.summary()
    finally:
        obs.set_active(None)


# -- chrome trace-event schema (shared writer, PR-12) -------------------------

def _assert_chrome_schema(path):
    """Minimal Chrome trace-event-format contract: a JSON object with a
    ``traceEvents`` list whose events all carry name/ph/pid, duration
    events numeric ts/dur, and instants a valid scope."""
    data = json.load(open(path))
    assert isinstance(data, dict) and isinstance(data["traceEvents"], list)
    assert data["traceEvents"], "empty trace"
    for e in data["traceEvents"]:
        assert isinstance(e["name"], str) and e["name"]
        assert e["ph"] in ("X", "i", "M", "B", "E")
        assert isinstance(e["pid"], int)
        if e["ph"] == "X":
            assert isinstance(e["ts"], (int, float)) and e["ts"] >= 0
            assert isinstance(e["dur"], (int, float)) and e["dur"] >= 0
        elif e["ph"] == "i":
            assert e.get("s", "t") in ("t", "p", "g")
        elif e["ph"] == "M":
            assert e["name"] in ("process_name", "thread_name")
            assert isinstance(e["args"]["name"], str)
    return data


def test_profiler_export_matches_chrome_schema(tmp_path):
    prof = Profiler(timer_only=True)
    prof.start()
    with RecordEvent("alpha"):
        with RecordEvent("beta"):
            pass
    prof.stop()
    path = str(tmp_path / "prof_schema.json")
    prof.export(path)
    _assert_chrome_schema(path)


def test_request_tracer_export_matches_chrome_schema(tmp_path):
    # the tracer goes through the same write_chrome_trace writer as the
    # profiler, so both exports must satisfy the same schema
    from paddle_tpu.observability.request_trace import RequestTracer
    tr = RequestTracer()
    tr.submit(0, 0.0)
    tr.admit(0, 0.5)
    tr.prefill_chunk(0, 0.5, 0.8, n_tokens=32, recompute=False)
    tr.phase("prefill", 0.5, 0.8, iteration=0)
    tr.decode([0], 1.0, 1.1, iteration=1)
    tr.evict(0, 1.2, n_preempted=1)
    tr.admit(0, 1.5, n_preempted=1)
    tr.prefill_chunk(0, 1.5, 1.9, n_tokens=33, recompute=True)
    tr.decode([0], 2.0, 2.1, iteration=4)
    tr.finish(0, 2.1, n_generated=2)
    path = tr.export_chrome(str(tmp_path / "req_schema.json"))
    data = _assert_chrome_schema(path)
    phs = {e["ph"] for e in data["traceEvents"]}
    assert {"M", "X", "i"} <= phs
    rows = {e["args"]["name"] for e in data["traceEvents"]
            if e["name"] == "thread_name"}
    assert "request 0" in rows and "engine/prefill" in rows
