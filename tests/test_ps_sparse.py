"""Sharded parameter-server sparse tables: accessors, entry threshold,
gradient merge, persistence, and the PS-backed SparseEmbedding layer
(ref: paddle/fluid/distributed/ps/table + python/paddle/distributed/ps)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.distributed import rpc
from paddle_tpu.distributed.ps import PSClient, SparseEmbedding, service


def _free_port():
    import socket
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


@pytest.fixture()
def ps_world():
    """One process hosting 2 logical servers + 1 worker (the rpc world is
    in-process; shard tables stay distinct via the #shard suffix)."""
    port = _free_port()
    rpc.init_rpc("trainer0", rank=0, world_size=1,
                 master_endpoint=f"127.0.0.1:{port}")
    # the single rpc name serves both logical servers in-process
    client = PSClient("trainer0", servers=["trainer0", "trainer0"])
    saved = dict(service._TABLES)
    yield client
    service._TABLES.clear()
    service._TABLES.update(saved)
    rpc.shutdown()


def test_sharded_pull_push_roundtrip(ps_world):
    client = ps_world
    client.create_sparse_table("emb", 4, accessor={"type": "sgd", "lr": 1.0})
    ids = np.array([0, 1, 2, 3, 7, 10], np.int64)
    rows0 = client.pull_sparse("emb", ids)
    assert rows0.shape == (6, 4)
    # shards are distinct tables: keys landed by parity
    names = set(service._TABLES)
    assert "emb#0" in names and "emb#1" in names
    even = service._TABLES["emb#0"]["rows"]
    assert set(even) == {0, 2, 10}
    # push unit grads; sgd lr=1.0 -> rows drop by exactly the grad
    g = np.ones((6, 4), np.float32)
    client.push_sparse("emb", ids, g)
    rows1 = client.pull_sparse("emb", ids)
    np.testing.assert_allclose(rows1, rows0 - 1.0, atol=1e-6)


def test_duplicate_ids_merge_before_apply(ps_world):
    """Duplicate ids in one push must be summed THEN applied once (with a
    nonlinear accessor, applying twice would differ)."""
    client = ps_world
    client.create_sparse_table("dup", 2,
                               accessor={"type": "adagrad", "lr": 0.5})
    base = client.pull_sparse("dup", [4])  # materialize the row
    client.push_sparse("dup", [4, 4], np.array([[1., 1.], [1., 1.]]))
    got = client.pull_sparse("dup", [4])[0]
    # merged grad = 2 -> g2 = 4, update = .5 * 2/2 = .5 (one apply)
    np.testing.assert_allclose(got, base[0] - 0.5, atol=1e-5)


def test_adam_accessor_matches_reference_math(ps_world):
    client = ps_world
    client.create_sparse_table(
        "adam_t", 3, accessor={"type": "adam", "lr": 0.1,
                               "beta1": 0.9, "beta2": 0.999})
    w0 = client.pull_sparse("adam_t", [6])[0].copy()
    g = np.array([0.3, -0.2, 0.05], np.float32)
    client.push_sparse("adam_t", [6], g[None])
    got = client.pull_sparse("adam_t", [6])[0]
    m = 0.1 * g
    v = 0.001 * g * g
    mhat = m / (1 - 0.9)
    vhat = v / (1 - 0.999)
    ref = w0 - 0.1 * mhat / (np.sqrt(vhat) + 1e-8)
    np.testing.assert_allclose(got, ref, rtol=1e-5)


def test_entry_threshold_gates_admission(ps_world):
    """Rows appear only after `entry_threshold` training pulls (frequency-
    gated feature admission); before that pulls return zeros."""
    client = ps_world
    client.create_sparse_table("gated", 4, entry_threshold=3)
    for _ in range(2):
        rows = client.pull_sparse("gated", [8])
        np.testing.assert_allclose(rows, 0.0)
    rows = client.pull_sparse("gated", [8])  # 3rd show: admitted
    assert np.abs(rows).sum() > 0
    # eval pulls don't count as shows
    client.create_sparse_table("gated2", 4, entry_threshold=1)
    rows = client.pull_sparse("gated2", [1], training=False)
    np.testing.assert_allclose(rows, 0.0)


def test_save_load_roundtrip(ps_world, tmp_path):
    client = ps_world
    client.create_sparse_table("persist", 4)
    before = client.pull_sparse("persist", [1, 2, 3])
    assert client.save_sparse_table("persist", str(tmp_path))
    # mutate, then restore
    client.push_sparse("persist", [1, 2, 3], np.ones((3, 4)), lr=1.0)
    assert client.load_sparse_table("persist", str(tmp_path))
    after = client.pull_sparse("persist", [1, 2, 3])
    np.testing.assert_allclose(after, before, atol=1e-6)


def test_sparse_embedding_layer_trains(ps_world):
    """End-to-end: PS-backed embedding + device-side dense head; embedding
    rows must move toward reducing the loss via the table accessor."""
    client = ps_world
    emb = SparseEmbedding(client, "layer_emb", 8,
                          accessor={"type": "sgd", "lr": 0.1})
    ids = paddle.to_tensor(np.array([[1, 2], [3, 1]], np.int64))
    target = paddle.to_tensor(np.zeros((2, 2, 8), np.float32))

    losses = []
    for _ in range(10):
        out = emb(ids)
        assert out.shape == [2, 2, 8]
        loss = ((out - target) ** 2).sum()
        loss.backward()
        losses.append(float(loss.numpy()))
    assert losses[-1] < losses[0] * 0.5, losses
    # duplicate id 1 appears twice per batch: merge path exercised
    st = client.stat()
    total_rows = sum(n for tables in st.values()
                     for kind, n in tables.values() if kind == "sparse")
    assert total_rows >= 3


def test_disk_spill_tier(tmp_path):
    """max_mem_rows caps the hot tier; cold rows spill to disk, survive
    there with their optimizer state, and promote back on access with
    identical values (ref: the reference's SSD sparse tables)."""
    service.create_sparse_table("spill_t", 4, accessor={"type": "sgd",
                                                        "lr": 1.0},
                                max_mem_rows=8,
                                spill_path=str(tmp_path / "spill.log"))
    try:
        # touch 32 ids: only <=8 stay in memory
        ids = list(range(32))
        first = service.pull_sparse("spill_t", ids)
        t = service._TABLES["spill_t"]
        assert len(t["rows"]) <= 8
        assert len(t["spill"].index) >= 24
        # stat counts BOTH tiers
        kind, n = service.stat()["spill_t"]
        assert (kind, n) == ("sparse", 32)
        # push to a SPILLED id: promoted, grad applied (w -= lr*g)
        victim = ids[0]
        assert victim not in t["rows"]
        g = np.ones((1, 4), np.float32)
        service.push_sparse("spill_t", [victim], g)
        got = service.pull_sparse("spill_t", [victim])
        np.testing.assert_allclose(got[0], first[0] - 1.0, rtol=1e-6)
        # pulls of spilled rows return the same values as when created
        again = service.pull_sparse("spill_t", ids[1:])
        np.testing.assert_allclose(again, first[1:], rtol=1e-6)
        # save merges both tiers; load with the cap re-spills the tail
        service.save_table("spill_t", str(tmp_path / "table.pkl"))
        service.load_table("spill_t2", str(tmp_path / "table.pkl"))
        restored = service.pull_sparse("spill_t2", ids[1:])
        np.testing.assert_allclose(restored, first[1:], rtol=1e-6)
        assert len(service._TABLES["spill_t2"]["rows"]) <= 8
    finally:
        service.drop_table("spill_t")
        service.drop_table("spill_t2")
