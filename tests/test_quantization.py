"""QAT/PTQ: fake-quant numerics, observer calibration, model conversion."""
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.quantization import (QAT, PTQ, AbsmaxObserver,
                                     FakeQuanterChannelWiseAbsMax,
                                     FakeQuanterWithAbsMax, HistObserver,
                                     QuantConfig, QuantedLinear,
                                     quant_dequant_abs_max)

R = np.random.RandomState(11)


def _model():
    return nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))


def test_quant_dequant_roundtrip():
    x = paddle.to_tensor(R.uniform(-1, 1, (4, 8)).astype(np.float32))
    s = paddle.to_tensor(np.float32(1.0))
    q = quant_dequant_abs_max(x, s, bit_length=8)
    # quantization error bounded by scale/qmax/2
    assert float(np.abs(q.numpy() - x.numpy()).max()) <= 1.0 / 127 / 2 + 1e-6


def test_ste_gradient_passes_through():
    x = paddle.to_tensor(R.uniform(-1, 1, (4, 8)).astype(np.float32),
                         stop_gradient=False)
    s = paddle.to_tensor(np.float32(1.0))
    out = quant_dequant_abs_max(x, s)
    out.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), np.ones((4, 8)), atol=1e-6)


def test_qat_swaps_and_trains():
    model = _model()
    cfg = QuantConfig(activation=FakeQuanterWithAbsMax,
                      weight=FakeQuanterChannelWiseAbsMax)
    qat = QAT(cfg)
    qmodel = qat.quantize(model, inplace=True)
    assert isinstance(qmodel._sub_layers["0"], QuantedLinear)
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=qmodel.parameters())
    x = paddle.to_tensor(R.rand(16, 8).astype(np.float32))
    y = paddle.to_tensor(R.randint(0, 4, (16,)))
    losses = []
    for _ in range(5):
        loss = paddle.nn.functional.cross_entropy(qmodel(x), y)
        loss.backward()
        opt.step(); opt.clear_grad()
        losses.append(float(loss.numpy()))
    assert losses[-1] < losses[0]

    infer = qat.convert(qmodel, inplace=True)
    assert isinstance(infer._sub_layers["0"], nn.Linear)
    out = infer(x)
    assert np.isfinite(out.numpy()).all()


def test_ptq_calibrate_convert():
    model = _model()
    model.eval()
    x = paddle.to_tensor(R.rand(32, 8).astype(np.float32))
    ref = model(x).numpy()

    cfg = QuantConfig(activation=AbsmaxObserver, weight=AbsmaxObserver)
    ptq = PTQ(cfg)
    qmodel = ptq.quantize(model, inplace=False)
    qmodel(x)  # calibration pass
    inf = ptq.convert(qmodel, inplace=True)
    got = inf(x).numpy()
    # int8 PTQ should stay close to fp32 on this tiny net
    assert np.abs(got - ref).max() < 0.15
    assert np.corrcoef(got.reshape(-1), ref.reshape(-1))[0, 1] > 0.99


def test_hist_observer_threshold():
    obs = HistObserver(percent=0.99)
    data = np.concatenate([R.uniform(-1, 1, 10000),
                           np.array([100.0])]).astype(np.float32)
    obs._observe(data)
    obs.cal_thresholds()
    # outlier must be clipped away
    assert obs.scales() < 5.0


def test_type_config_override():
    model = _model()
    cfg = QuantConfig(activation=None, weight=None)
    cfg.add_type_config(nn.Linear, activation=FakeQuanterWithAbsMax,
                        weight=FakeQuanterWithAbsMax)
    q = QAT(cfg).quantize(model, inplace=True)
    assert isinstance(q._sub_layers["0"], QuantedLinear)
