"""Flash-ring attention (Pallas per-block kernels + lse merge) correctness.

Ref: SURVEY.md §5.7 (sep/context parallelism). The flash ring must match
full-sequence attention exactly in fwd AND grads — including the causal
block-skipping path (src > my blocks contribute nothing) and GQA.
"""
import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P
from jax.experimental.shard_map import shard_map

import paddle_tpu  # noqa: F401  (jax config)
from paddle_tpu.parallel.ring_attention import ring_attention


def _mesh(n):
    devs = jax.devices("cpu")[:n]
    return Mesh(np.array(devs), ("sep",))


def _ring_fn(mesh, causal, impl):
    fn = functools.partial(ring_attention, axis_name="sep", causal=causal,
                           impl=impl)
    return shard_map(fn, mesh=mesh,
                     in_specs=(P(None, "sep"), P(None, "sep"), P(None, "sep")),
                     out_specs=P(None, "sep"), check_rep=False)


def _reference(q, k, v, causal):
    qf, kf, vf = (x.astype(jnp.float32) for x in (q, k, v))
    H, Hkv = q.shape[2], k.shape[2]
    if Hkv != H:
        kf = jnp.repeat(kf, H // Hkv, axis=2)
        vf = jnp.repeat(vf, H // Hkv, axis=2)
    s = jnp.einsum("bqhd,bkhd->bhqk", qf, kf) / np.sqrt(q.shape[-1])
    if causal:
        S = q.shape[1]
        mask = np.tril(np.ones((S, S), bool))
        s = jnp.where(mask[None, None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, vf).astype(q.dtype)


@pytest.mark.parametrize("causal", [True, False])
def test_ring_flash_matches_full(causal):
    n = 4
    B, S, H, D = 1, 4 * 128, 2, 64  # S_local = 128: Pallas block path
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(B, S, H, D), jnp.float32)
    k = jnp.asarray(rng.randn(B, S, H, D), jnp.float32)
    v = jnp.asarray(rng.randn(B, S, H, D), jnp.float32)
    mesh = _mesh(n)
    out = _ring_fn(mesh, causal, "flash")(q, k, v)
    ref = _reference(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_ring_flash_grads_match(causal):
    n = 4
    B, S, H, D = 1, 4 * 128, 2, 64
    rng = np.random.RandomState(1)
    q = jnp.asarray(rng.randn(B, S, H, D), jnp.float32)
    k = jnp.asarray(rng.randn(B, S, H, D), jnp.float32)
    v = jnp.asarray(rng.randn(B, S, H, D), jnp.float32)
    mesh = _mesh(n)
    w = jnp.asarray(rng.randn(B, S, H, D), jnp.float32)  # non-uniform cotangent

    ring = _ring_fn(mesh, causal, "flash")

    def loss_ring(q, k, v):
        return jnp.sum(ring(q, k, v).astype(jnp.float32) * w)

    def loss_ref(q, k, v):
        return jnp.sum(_reference(q, k, v, causal).astype(jnp.float32) * w)

    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g_ring, g_ref, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4,
                                   atol=2e-4, err_msg=f"d{name}")


@pytest.mark.slow   # GQA kv routing stays covered in tier-1 by the ulysses gqa tests
def test_ring_flash_gqa():
    n = 4
    B, S, H, D = 1, 4 * 128, 4, 64
    rng = np.random.RandomState(2)
    q = jnp.asarray(rng.randn(B, S, H, D), jnp.float32)
    k = jnp.asarray(rng.randn(B, S, 2, D), jnp.float32)
    v = jnp.asarray(rng.randn(B, S, 2, D), jnp.float32)
    mesh = _mesh(n)
    out = _ring_fn(mesh, True, "flash")(q, k, v)
    ref = _reference(q, k, v, True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)

    # grads flow through the GQA repeat (kv grads sum over repeated heads)
    ring = _ring_fn(mesh, True, "flash")
    gk = jax.grad(lambda k: jnp.sum(ring(q, k, v)))(k)
    gk_ref = jax.grad(lambda k: jnp.sum(_reference(q, k, v, True)))(k)
    np.testing.assert_allclose(np.asarray(gk), np.asarray(gk_ref),
                               rtol=2e-4, atol=2e-4)


def test_ring_small_shards_fall_back():
    # S_local = 32 is not 128-aligned: flash impl must transparently use the
    # xla path and still be exact
    n = 4
    B, S, H, D = 2, 4 * 32, 2, 16
    rng = np.random.RandomState(3)
    q = jnp.asarray(rng.randn(B, S, H, D), jnp.float32)
    k = jnp.asarray(rng.randn(B, S, H, D), jnp.float32)
    v = jnp.asarray(rng.randn(B, S, H, D), jnp.float32)
    mesh = _mesh(n)
    out = _ring_fn(mesh, True, "flash")(q, k, v)
    ref = _reference(q, k, v, True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_llama_sep_flash_path():
    """The model's sep path with use_flash=True at 128-aligned shards runs
    the Pallas flash ring (interpret mode on CPU) and matches serial loss."""
    from paddle_tpu.models.llama import (ParallelConfig, build_train_step,
                                         llama_tiny)
    cfg = llama_tiny(vocab=64, hidden=32, layers=2, heads=2, kv_heads=2,
                     inter=64, seq=512)
    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg.vocab_size, (2, 512)).astype(np.int32)
    labels = np.roll(ids, -1, axis=1).astype(np.int32)

    step, p, o = build_train_step(cfg, ParallelConfig(use_flash=False,
                                                      remat=False), lr=1e-3)
    _, _, l_ref = step(p, o, ids, labels)

    par = ParallelConfig(dp=2, sep=4, use_flash=True, remat=False)
    step2, p2, o2 = build_train_step(cfg, par, lr=1e-3)
    _, _, l_sep = step2(p2, o2, ids, labels)
    np.testing.assert_allclose(float(l_sep), float(l_ref), rtol=2e-4)
