"""RooflineLedger tests: classification math, the explicit unattributed
remainder line, model- vs measured-mode feeds, the TrainStep integration
(bit-identical losses with the ledger on), peak-FLOPs provenance, env
gating, the device-trace merge, and the flagship component specs."""
import gzip
import json
import os
import warnings

import jax
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.jit import TrainStep
from paddle_tpu.observability import ledger as led
from paddle_tpu.observability import metrics as met
from paddle_tpu.observability.ledger import (RooflineLedger, ledger_dir,
                                             ledger_enabled,
                                             merge_device_trace)
from paddle_tpu.optimizer import AdamW


def _ledger(**kw):
    kw.setdefault("peak_flops", 1e12)   # 1 TFLOP/s -> 1e9 flops = 1 ms
    kw.setdefault("hbm_bw", 1e9)        # 1 GB/s    -> 1e6 bytes = 1 ms
    return RooflineLedger(name="t", **kw)


# -- classification -----------------------------------------------------------

def test_classify_compute_vs_memory_bound():
    lg = _ledger()
    c = lg.classify(flops=1e9, bytes_accessed=10)
    assert c["bound"] == "compute"
    np.testing.assert_allclose(c["compute_ms"], 1.0)
    np.testing.assert_allclose(c["roofline_ms"], 1.0)
    m = lg.classify(flops=10, bytes_accessed=1e6)
    assert m["bound"] == "memory"
    np.testing.assert_allclose(m["memory_ms"], 1.0)
    np.testing.assert_allclose(m["roofline_ms"], 1.0)
    # roofline time is the MAX of the two — never the sum
    both = lg.classify(flops=2e9, bytes_accessed=1e6)
    np.testing.assert_allclose(both["roofline_ms"], 2.0)
    assert both["bound"] == "compute"


def test_classify_unknown_platform_degrades_to_unknown():
    lg = _ledger()
    lg.peak_flops = lg.hbm_bw = None
    c = lg.classify(1e9, 1e6)
    assert c == {"compute_ms": None, "memory_ms": None,
                 "bound": "unknown", "roofline_ms": None}


def test_hbm_bw_table_and_unknown_kind():
    class Dev:
        device_kind = "TPU v4"
    bw, src = led.hbm_bw_per_device(Dev())
    assert bw == 1228e9 and src == "table:v4"

    class Weird:
        device_kind = "quantum-abacus"
    bw, src = led.hbm_bw_per_device(Weird())
    assert bw is None and src == "unknown:quantum-abacus"


# -- report shape + the explicit remainder line -------------------------------

def test_report_has_explicit_unattributed_remainder_line():
    lg = _ledger()
    lg.add("matmul", flops=4e9, bytes_accessed=100, time_ms=6.0)
    rep = lg.report(step_time_ms=10.0)
    assert rep["step_ms"] == 10.0
    assert rep["attributed_ms"] == 6.0
    np.testing.assert_allclose(rep["unattributed_ms"], 4.0)
    np.testing.assert_allclose(rep["unattributed_frac"], 0.4)
    rem = rep["lines"][-1]
    assert rem["name"] == "unattributed"
    assert rem["bound"] == "remainder"
    np.testing.assert_allclose(rem["attributed_ms"], 4.0)
    np.testing.assert_allclose(rem["frac_of_step"], 0.4)
    # ... and it renders in report_lines like any other row
    text = "\n".join(lg.report_lines(10.0))
    assert "unattributed" in text and "[remainder]" in text


def test_report_remainder_clamps_at_zero():
    lg = _ledger()
    lg.add("matmul", flops=1.0, time_ms=12.0)  # attributes MORE than step
    rep = lg.report(step_time_ms=10.0)
    assert rep["unattributed_ms"] == 0.0
    assert rep["unattributed_frac"] == 0.0


def test_measured_mode_achieved_frac():
    lg = _ledger()
    lg.add("matmul", flops=1e9, time_ms=2.0)   # roofline 1 ms, ran in 2 ms
    line = lg.report(step_time_ms=4.0)["lines"][0]
    assert line["measured"] is True
    np.testing.assert_allclose(line["achieved_frac"], 0.5)
    np.testing.assert_allclose(line["frac_of_step"], 0.5)


def test_model_mode_ingest_uses_roofline_time():
    lg = _ledger()
    n = lg.ingest({"rms_norm.fwd": {"calls": 3, "flops": 1e9,
                                    "bytes_accessed": 10,
                                    "transcendentals": 5.0},
                   "never_ran": {"calls": 0, "flops": 1e12}})
    assert n == 1  # zero-call entries are not lines
    line = lg.report(step_time_ms=2.0)["lines"][0]
    assert line["name"] == "rms_norm.fwd" and line["calls"] == 3
    assert line["measured"] is False and line["time_ms"] is None
    # attribution falls back to the roofline (optimistic-floor) time
    np.testing.assert_allclose(line["attributed_ms"], 1.0)
    np.testing.assert_allclose(line["frac_of_step"], 0.5)


def test_on_step_window_and_best_of():
    lg = _ledger()
    for s in (0.004, 0.002, 0.003, 0.0, -1.0):  # non-positive ignored
        lg.on_step(s)
    assert lg.steps == 5
    np.testing.assert_allclose(lg.step_time_ms(), 2.0)
    # report with no explicit step time uses the recorded best
    np.testing.assert_allclose(lg.report()["step_ms"], 2.0)


def test_write_appends_jsonl(tmp_path):
    lg = _ledger()
    lg.add("k", flops=1e9, time_ms=1.5)
    path = str(tmp_path / "sub" / "ledger.jsonl")
    assert lg.write(path=path, step_time_ms=3.0) == path
    lg.write(path=path, step_time_ms=3.0)
    recs = [json.loads(l) for l in open(path)]
    assert len(recs) == 2
    assert recs[0]["lines"][-1]["name"] == "unattributed"


# -- env gating ---------------------------------------------------------------

def test_ledger_env_gating(tmp_path, monkeypatch):
    monkeypatch.delenv(led.ENV_LEDGER, raising=False)
    assert ledger_enabled() is False
    monkeypatch.setenv(led.ENV_LEDGER, "1")
    assert ledger_enabled() is True
    assert ledger_enabled(explicit=False) is False  # explicit arg wins
    monkeypatch.setenv(led.ENV_LEDGER, "0")
    assert ledger_enabled() is False
    assert ledger_enabled(explicit=True) is True
    monkeypatch.setenv(led.ENV_LEDGER_DIR, str(tmp_path))
    assert ledger_dir() == str(tmp_path)


# -- peak-FLOPs provenance (StepMetrics satellite) ----------------------------

def test_peak_flops_unknown_platform_warns_once_naming_it(monkeypatch):
    monkeypatch.delenv(met.ENV_PEAK_FLOPS, raising=False)

    class Dev:
        device_kind = "quantum-abacus"
    met._PEAK_WARNED.discard("quantum-abacus")
    with pytest.warns(UserWarning, match="quantum-abacus"):
        flops, src = met.peak_flops_info(Dev())
    assert flops is None and src == "unknown:quantum-abacus"
    # once per run: the second lookup is silent
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        flops, src = met.peak_flops_info(Dev())
    assert flops is None and src == "unknown:quantum-abacus"
    met._PEAK_WARNED.discard("quantum-abacus")


def test_step_metrics_records_carry_mfu_peak_source():
    m = met.StepMetrics("t", n_devices=1, peak_flops=1e12)
    assert m.mfu_peak_source == "arg"
    rec = m.step(step_time_s=1e-3, tokens=4)
    assert rec["mfu_peak_source"] == "arg"
    assert m.summary()["mfu_peak_source"] == "arg"


def test_peak_flops_env_override_wins(monkeypatch):
    monkeypatch.setenv(met.ENV_PEAK_FLOPS, "2.5e12")
    flops, src = met.peak_flops_info()
    assert flops == 2.5e12 and src == "env"


# -- TrainStep integration: measurement-only ----------------------------------

def _run_tiny(n_calls=6, **kw):
    paddle.seed(3)
    model = nn.Sequential(nn.Linear(8, 16), nn.GELU(), nn.Linear(16, 4))
    opt = AdamW(learning_rate=1e-2, parameters=model.parameters())
    step = TrainStep(model, lambda o, l: paddle.mean((o - l) ** 2), opt,
                     **kw)
    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.randn(4, 8).astype(np.float32))
    y = paddle.to_tensor(rng.randn(4, 4).astype(np.float32))
    losses = [float(step(x, labels=y)) for _ in range(n_calls)]
    return step, losses


def test_train_step_ledger_losses_bit_identical(monkeypatch):
    monkeypatch.delenv(led.ENV_LEDGER, raising=False)
    step_off, losses_off = _run_tiny()
    assert step_off.ledger is None  # off by default
    step_on, losses_on = _run_tiny(ledger=True)
    assert isinstance(step_on.ledger, RooflineLedger)
    # the measurement-only contract: EXACT equality, not allclose
    assert losses_on == losses_off
    # and the ledger actually observed the run
    assert step_on.ledger.steps >= 1
    rep = step_on.ledger.report()
    assert rep["step_ms"] and rep["lines"][-1]["name"] == "unattributed"


def test_train_step_ledger_instance_arg_wins(monkeypatch):
    monkeypatch.delenv(led.ENV_LEDGER, raising=False)
    mine = RooflineLedger(name="mine")
    step, _ = _run_tiny(n_calls=3, ledger=mine)
    assert step.ledger is mine and mine.steps >= 1


# -- device-trace merge -------------------------------------------------------

def _fake_profile_dir(tmp_path, events):
    d = tmp_path / "prof" / "plugins" / "profile" / "run1"
    d.mkdir(parents=True)
    with gzip.open(d / "host.trace.json.gz", "wt") as fh:
        json.dump({"traceEvents": events}, fh)
    return str(tmp_path / "prof")


def test_merge_device_trace_min_ts_alignment(tmp_path):
    dev = [{"name": "fusion.1", "ph": "X", "pid": 1, "tid": 0,
            "ts": 1000, "dur": 50},
           {"name": "fusion.2", "ph": "X", "pid": 1, "tid": 0,
            "ts": 1500, "dur": 20},
           {"name": "process_name", "ph": "M", "pid": 1,
            "args": {"name": "device"}}]
    host = [{"name": "step", "ph": "X", "pid": 0, "tid": 0,
             "ts": 500000, "dur": 900}]
    out_path = str(tmp_path / "merged.json")
    res = merge_device_trace(_fake_profile_dir(tmp_path, dev),
                             host_events=host, out_path=out_path)
    assert res["device_events"] == 3 and res["host_events"] == 1
    assert res["aligned_on"] is None and res["out_path"] == out_path
    tr = json.load(open(out_path))["traceEvents"]
    by_name = {e["name"]: e for e in tr if e.get("ph") != "M"}
    # both streams re-zeroed on their own earliest event
    assert by_name["fusion.1"]["ts"] == 0
    assert by_name["fusion.2"]["ts"] == 500
    assert by_name["step"]["ts"] == 0
    # host spans live on the dedicated host pid row
    assert by_name["step"]["pid"] == led._HOST_PID
    meta = [e for e in tr if e.get("ph") == "M"
            and e.get("pid") == led._HOST_PID]
    assert meta and meta[0]["args"]["name"].startswith("host")


def test_merge_device_trace_align_on_shared_span(tmp_path):
    dev = [{"name": "warmup", "ph": "X", "pid": 1, "ts": 100, "dur": 5},
           {"name": "jit_step7/decoder.attn", "ph": "X", "pid": 1,
            "ts": 1500, "dur": 80}]
    host = [{"name": "setup", "ph": "X", "pid": 0, "ts": 7000, "dur": 10},
            {"name": "step7", "ph": "X", "pid": 0, "ts": 9000, "dur": 100}]
    res = merge_device_trace(_fake_profile_dir(tmp_path, dev),
                             host_events=host, align_on="step7")
    assert res["aligned_on"] == "step7"
    by_name = {e["name"]: e for e in res["events"] if e.get("ph") != "M"}
    # the shared span's first occurrence is pinned to t=0 on BOTH sides
    assert by_name["jit_step7/decoder.attn"]["ts"] == 0
    assert by_name["step7"]["ts"] == 0
    assert by_name["warmup"]["ts"] == -1400
    assert by_name["setup"]["ts"] == -2000


def test_merge_device_trace_missing_align_falls_back(tmp_path):
    dev = [{"name": "fusion.1", "ph": "X", "pid": 1, "ts": 300, "dur": 5}]
    res = merge_device_trace(_fake_profile_dir(tmp_path, dev),
                             host_events=[], align_on="nowhere")
    assert res["aligned_on"] is None
    by_name = {e["name"]: e for e in res["events"] if e.get("ph") != "M"}
    assert by_name["fusion.1"]["ts"] == 0


# -- flagship component specs -------------------------------------------------

def test_flagship_component_specs_shape_and_runnable():
    from paddle_tpu.models.llama import llama_tiny
    config = llama_tiny(vocab=64, hidden=32, layers=2, heads=2, kv_heads=2,
                        inter=64, seq=32)
    specs = led.flagship_component_specs(config, batch=2, seq=32,
                                         use_flash=False)
    names = [s["name"] for s in specs]
    assert names == ["attention_fwd", "attention_bwd", "ffn_fwd",
                     "ffn_bwd", "qkvo_proj_fwd", "qkvo_proj_bwd",
                     "lm_head_loss_fwd", "lm_head_loss_bwd", "optimizer"]
    for s in specs:
        assert set(s) == {"name", "build", "mult", "flops",
                          "bytes_accessed", "transcendentals"}
        assert s["flops"] > 0 and s["bytes_accessed"] > 0
        assert s["mult"] >= 1
    # per-layer components scale by L; bwd costs exceed fwd
    assert specs[0]["mult"] == config.num_hidden_layers
    assert specs[1]["flops"] > specs[0]["flops"]
    # a build() hands back (fn, args) the caller's timer can run
    fn, args = specs[2]["build"]()  # ffn_fwd
    out = jax.jit(fn)(*args)
    assert np.isfinite(np.asarray(out, np.float32)).all()
    fn, args = specs[8]["build"]()  # optimizer
    p2, m2, v2 = jax.jit(fn)(*args)
    assert p2.shape == args[0].shape


def test_flagship_specs_feed_measured_ledger():
    from paddle_tpu.models.llama import llama_tiny
    config = llama_tiny(vocab=64, hidden=32, layers=2, heads=2, kv_heads=2,
                        inter=64, seq=32)
    lg = _ledger()
    for s in led.flagship_component_specs(config, 2, 32, use_flash=False):
        lg.add(s["name"], flops=s["mult"] * s["flops"],
               bytes_accessed=s["mult"] * s["bytes_accessed"],
               transcendentals=s["mult"] * s["transcendentals"],
               time_ms=s["mult"] * 0.1, calls=s["mult"])
    rep = lg.report(step_time_ms=2.0)
    assert len(rep["lines"]) == 9 + 1  # components + remainder
    assert all(l["achieved_frac"] is not None
               for l in rep["lines"][:-1])
    assert all(l["bound"] in ("compute", "memory")
               for l in rep["lines"][:-1])
