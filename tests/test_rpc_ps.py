"""distributed.rpc + parameter-server mode: in-process and multi-process."""
import os
import pickle
import subprocess
import sys

import numpy as np

import paddle_tpu  # noqa: F401


def _free_port():
    import socket
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


def _double(x):
    return x * 2


def _boom():
    raise ValueError("intentional")


def test_rpc_single_world():
    from paddle_tpu.distributed import rpc
    port = _free_port()
    rpc.init_rpc("worker0", rank=0, world_size=1,
                 master_endpoint=f"127.0.0.1:{port}")
    assert rpc.rpc_sync("worker0", _double, args=(21,)) == 42
    fut = rpc.rpc_async("worker0", _double, args=(5,))
    assert fut.result(timeout=10) == 10
    info = rpc.get_current_worker_info()
    assert info.name == "worker0" and info.rank == 0
    rpc.shutdown()


def test_rpc_error_propagates():
    from paddle_tpu.distributed import rpc
    port = _free_port()
    rpc.init_rpc("workerE", rank=0, world_size=1,
                 master_endpoint=f"127.0.0.1:{port}")
    try:
        rpc.rpc_sync("workerE", _boom)
        raised = False
    except RuntimeError as e:
        raised = "intentional" in str(e)
    finally:
        rpc.shutdown()
    assert raised


def test_rpc_rejects_unauthenticated():
    """A connection without the shared-secret preamble must be dropped
    before any unpickling (no code execution for strangers)."""
    import socket
    import struct
    from paddle_tpu.distributed import rpc
    port = _free_port()
    rpc.init_rpc("workerA", rank=0, world_size=1,
                 master_endpoint=f"127.0.0.1:{port}")
    try:
        info = rpc.get_current_worker_info()
        payload = pickle.dumps({"op": "call", "fn": _double,
                                "args": (1,), "kwargs": {}})
        with socket.create_connection((info.ip, info.port), timeout=5) as s:
            # no token preamble: server must close without replying
            s.sendall(struct.pack(">I", len(payload)) + payload)
            s.settimeout(2.0)
            try:
                data = s.recv(1024)
            except (socket.timeout, ConnectionError):
                data = b""
        assert data == b""
        # wrong token: also dropped (single send so the server's early close
        # can't race a second sendall into BrokenPipeError)
        with socket.create_connection((info.ip, info.port), timeout=5) as s:
            s.sendall(b"\x00" * 32 + struct.pack(">I", len(payload)) + payload)
            s.settimeout(2.0)
            try:
                data = s.recv(1024)
            except (socket.timeout, ConnectionError):
                data = b""
        assert data == b""
        # the authenticated path still works
        assert rpc.rpc_sync("workerA", _double, args=(4,)) == 8
    finally:
        rpc.shutdown()


def test_ps_tables_inprocess():
    from paddle_tpu.distributed import rpc
    from paddle_tpu.distributed.ps import PSClient, service
    service._TABLES.clear()
    port = _free_port()
    rpc.init_rpc("ps_server:0", rank=0, world_size=1,
                 master_endpoint=f"127.0.0.1:{port}")
    client = PSClient("ps_server:0")
    assert client.create_dense_table("w", [4, 3])
    w0 = client.pull_dense("w")
    assert w0.shape == (4, 3) and (w0 == 0).all()
    g = np.ones((4, 3), np.float32)
    client.push_dense("w", g, lr=0.1)
    np.testing.assert_allclose(client.pull_dense("w"), -0.1 * g)

    assert client.create_sparse_table("emb", 8)
    rows = client.pull_sparse("emb", [3, 7, 3])
    assert rows.shape == (3, 8)
    np.testing.assert_allclose(rows[0], rows[2])  # same id, same row
    client.push_sparse("emb", [3], np.ones((1, 8), np.float32), lr=0.5)
    rows2 = client.pull_sparse("emb", [3])
    np.testing.assert_allclose(rows2[0], rows[0] - 0.5)
    st = client.stat()
    assert st["w"][0] == "dense" and st["emb"] == ("sparse", 2)
    rpc.shutdown()
    service._TABLES.clear()


_WORKER_SCRIPT = r"""
import os, sys
import numpy as np
sys.path.insert(0, os.environ["REPO"])
from paddle_tpu.distributed import rpc

def fn(a, b):
    return a + b

rank = int(sys.argv[1])
port = sys.argv[2]
rpc.init_rpc(f"w{rank}", rank=rank, world_size=2,
             master_endpoint=f"127.0.0.1:{port}")
if rank == 0:
    out = rpc.rpc_sync("w1", fn, args=(40, 2))
    assert out == 42, out
    print("RPC_OK")
else:
    import time
    time.sleep(2.0)
rpc.shutdown()
"""


def test_rpc_two_processes(tmp_path):
    script = tmp_path / "rpc_worker.py"
    script.write_text(_WORKER_SCRIPT)
    port = str(_free_port())
    env = dict(os.environ, REPO="/root/repo",
               PYTHONPATH="/root/repo:" + os.environ.get("PYTHONPATH", ""))
    procs = [subprocess.Popen([sys.executable, str(script), str(r), port],
                              env=env, stdout=subprocess.PIPE,
                              stderr=subprocess.STDOUT)
             for r in (0, 1)]
    outs = [p.communicate(timeout=120)[0].decode() for p in procs]
    assert procs[0].returncode == 0, outs[0]
    assert procs[1].returncode == 0, outs[1]
    assert "RPC_OK" in outs[0]


_PS_SERVER_SCRIPT = r"""
import os, sys
sys.path.insert(0, os.environ["REPO"])
from paddle_tpu.distributed import fleet

assert fleet.is_server()
print("PS_SERVER_STARTING", flush=True)  # before init: rendezvous blocks
fleet.init_server()                      # until the trainer joins
print("PS_SERVER_UP", flush=True)
fleet.run_server()                       # blocks; parent terminates us
"""


def test_fleet_ps_mode_cross_process(tmp_path):
    """Reference PS flow: a PSERVER process (init_server/run_server) and a
    TRAINER in this process (init_worker, table ops, stop_worker), roles and
    endpoints from the PADDLE_* env the launcher would set."""
    import time
    port = _free_port()
    saved_env = dict(os.environ)
    env = dict(os.environ)
    env.update({
        "REPO": os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "TRAINING_ROLE": "PSERVER",
        "PADDLE_PSERVERS_IP_PORT_LIST": f"127.0.0.1:{port}",
        "PADDLE_PSERVER_ID": "0",
        "PADDLE_MASTER": f"127.0.0.1:{port}",
        "PADDLE_WORLD_SIZE": "2",
        "PADDLE_RANK": "0",
        "JAX_PLATFORMS": "cpu",
    })
    script = tmp_path / "ps_server.py"
    script.write_text(_PS_SERVER_SCRIPT)
    srv = subprocess.Popen([sys.executable, str(script)], env=env,
                           stdout=subprocess.PIPE, text=True)
    try:
        line = srv.stdout.readline()
        assert "PS_SERVER_STARTING" in line, line

        os.environ.update({
            "TRAINING_ROLE": "TRAINER",
            "PADDLE_PSERVERS_IP_PORT_LIST": f"127.0.0.1:{port}",
            "PADDLE_MASTER": f"127.0.0.1:{port}",
            "PADDLE_WORLD_SIZE": "2",
            "PADDLE_RANK": "1",
            "PADDLE_TRAINER_ID": "0",
        })
        from paddle_tpu.distributed import fleet
        assert fleet.is_worker()
        client = fleet.init_worker()
        assert client.create_sparse_table("fleet_emb", 4)
        rows = client.pull_sparse("fleet_emb", [1, 2, 3])
        assert rows.shape == (3, 4)
        client.push_sparse("fleet_emb", [1], np.ones((1, 4)), lr=1.0)
        rows2 = client.pull_sparse("fleet_emb", [1])
        np.testing.assert_allclose(rows2[0], rows[0] - 1.0, atol=1e-6)
        fleet.stop_worker()
    finally:
        srv.terminate()
        srv.wait(timeout=10)
        os.environ.clear()
        os.environ.update(saved_env)


def test_ctr_accessor_stats_and_shrink():
    """CTR sparse table (ref: ctr_common_accessor): pushes carry show/click
    increments; shrink decays the stats and evicts low-score rows."""
    from paddle_tpu.distributed import rpc
    from paddle_tpu.distributed.ps import PSClient, service
    service._TABLES.clear()
    port = _free_port()
    rpc.init_rpc("ps_server:0", rank=0, world_size=1,
                 master_endpoint=f"127.0.0.1:{port}")
    client = PSClient("ps_server:0")
    assert client.create_sparse_table(
        "ctr_emb", 4, accessor={"type": "ctr", "lr": 0.1,
                                "show_coeff": 0.2, "click_coeff": 1.0})
    client.pull_sparse("ctr_emb", [1, 2])     # materialize rows
    g = np.ones((2, 4), np.float32)
    # row 1: hot (many shows + clicks); row 2: cold
    client.push_sparse("ctr_emb", [1, 2], g, shows=[100.0, 1.0],
                       clicks=[10.0, 0.0])
    t = service._TABLES["ctr_emb"]
    assert t["rows"][1]["state"]["show"] == 100.0
    assert t["rows"][1]["state"]["click"] == 10.0
    # duplicate-id merge sums the stats too
    client.push_sparse("ctr_emb", [1, 1], np.zeros((2, 4), np.float32),
                       shows=[1.0, 2.0], clicks=[0.0, 1.0])
    assert t["rows"][1]["state"]["show"] == 103.0
    assert t["rows"][1]["state"]["click"] == 11.0
    # shrink: decay 0.5, threshold 1.0 -> cold row 2 evicted, hot row 1 kept
    evicted = client.shrink_sparse_table("ctr_emb", score_threshold=1.0,
                                         decay=0.5)
    assert evicted == 1
    assert 1 in t["rows"] and 2 not in t["rows"]
    assert t["rows"][1]["state"]["show"] == 103.0 * 0.5
    rpc.shutdown()
    service._TABLES.clear()


def test_geo_sgd_two_workers():
    """geo-SGD (ref: GeoCommunicator): two workers train locally and sync
    their parameter deltas every k steps; both converge to the merged
    global weights containing each other's updates."""
    from paddle_tpu.distributed import rpc
    from paddle_tpu.distributed.ps import PSClient, service
    service._TABLES.clear()
    port = _free_port()
    rpc.init_rpc("ps_server:0", rank=0, world_size=1,
                 master_endpoint=f"127.0.0.1:{port}")
    a = PSClient("ps_server:0")
    b = PSClient("ps_server:0")
    _, w_a = a.init_geo("geo_w", [2, 2], sync_steps=2)
    _, w_b = b.init_geo("geo_w", [2, 2], sync_steps=2)

    # worker A: two local steps of +1 each; second geo_step syncs
    w_a = w_a + 1.0
    w_a = a.geo_step("geo_w", w_a)          # step 1: local only
    w_a = w_a + 1.0
    w_a = a.geo_step("geo_w", w_a)          # step 2: pushes delta=+2, pulls
    np.testing.assert_allclose(w_a, np.full((2, 2), 2.0))

    # worker B trained in parallel from the ORIGINAL zeros: -1 per step
    w_b = w_b - 1.0
    w_b = b.geo_step("geo_w", w_b)
    w_b = w_b - 1.0
    w_b = b.geo_step("geo_w", w_b)          # pushes delta=-2 onto A's +2
    np.testing.assert_allclose(w_b, np.zeros((2, 2)))
    # A's next sync sees B's contribution merged in
    w_a = a.geo_step("geo_w", w_a)
    w_a = a.geo_step("geo_w", w_a)          # delta 0, pulls merged global
    np.testing.assert_allclose(w_a, np.zeros((2, 2)))
    rpc.shutdown()
    service._TABLES.clear()
