"""Continuous-batching serving engine (paddle_tpu/inference/engine.py).

Scheduler invariants pinned here:
  * token parity: continuous batching + chunked prefill + the paged KV
    cache produce the SAME greedy tokens as the contiguous-cache
    ``greedy_generate`` path, per request;
  * no block leaks: the pool returns to fully-free after every run,
    including runs with preemption;
  * deterministic replay: the same arrival trace replays to an
    identical event log and identical tokens;
  * preempt-by-eviction: when the pool runs dry mid-decode the
    youngest sequence is evicted, re-prefilled on readmission, and
    still produces the greedy reference tokens (recompute semantics).

Tiny model, pallas interpret mode on CPU. The two engine scenarios run
once in module fixtures; tests assert on their results.
"""
import numpy as np
import pytest

import jax.numpy as jnp

from paddle_tpu.inference import (BlockPool, InferenceEngine, Request,
                                  ServeConfig, pad_table)
from paddle_tpu.models.llama import (greedy_generate, init_llama_params,
                                     llama_tiny)
from paddle_tpu.ops import _common


@pytest.fixture(autouse=True)
def _interpret():
    with _common.interpret_mode(True):
        yield


@pytest.fixture(scope="module")
def model():
    cfg = llama_tiny(vocab=96, hidden=64, layers=1, heads=4, kv_heads=2,
                     seq=512)
    return cfg, init_llama_params(cfg, seed=3)


def _greedy_ref(model, prompt, n_new):
    cfg, params = model
    with _common.interpret_mode(True):
        out = greedy_generate(params, jnp.asarray([prompt], jnp.int32), cfg,
                              n_new)
    return np.asarray(out)[0].tolist()


@pytest.fixture(scope="module")
def basic_run(model):
    """Two mixed-length prompts (one multi-chunk, multi-block) through
    the engine twice on the same deterministic trace."""
    cfg, params = model
    rng = np.random.RandomState(0)
    prompts = [rng.randint(1, 96, size=n).tolist() for n in (7, 130)]
    serve = ServeConfig(block_size=128, num_blocks=10, max_batch=2,
                        prefill_chunk=32, max_seq_len=512)

    def one():
        eng = InferenceEngine(params, cfg, serve, record_events=True)
        reqs = [Request(p, max_new_tokens=5, arrival=float(i))
                for i, p in enumerate(prompts)]
        stats = eng.run(reqs, deterministic=True)
        return eng, stats

    with _common.interpret_mode(True):
        eng, stats = one()
        eng2, _ = one()
    return {"prompts": prompts, "eng": eng, "stats": stats, "eng2": eng2}


def test_engine_matches_greedy_generate(model, basic_run):
    for i, p in enumerate(basic_run["prompts"]):
        got = [s for s in basic_run["eng"].finished
               if s.req.request_id == i][0].generated
        assert got == _greedy_ref(model, p, 5), f"request {i}"


def test_no_block_leaks(basic_run):
    eng = basic_run["eng"]
    assert eng.pool.used_blocks == 0
    assert eng.pool.free_blocks == eng.serve.num_blocks - 1


def test_deterministic_replay(basic_run):
    eng, eng2 = basic_run["eng"], basic_run["eng2"]
    assert eng.events == eng2.events
    toks = lambda e: {s.req.request_id: s.tokens for s in e.finished}
    assert toks(eng) == toks(eng2)


def test_bounded_compiles(basic_run):
    """One compile per bucketed shape: prefill chunk + decode buckets."""
    stats = basic_run["stats"]
    assert set(stats["compiles"]) <= {"prefill_32", "decode_1", "decode_2"}


@pytest.fixture(scope="module")
def evict_run(model):
    """Pool sized so three one-block sequences admit, then starve when
    each crosses its block boundary mid-decode: 4 usable blocks, three
    120-token prompts growing past 128 cached tokens."""
    cfg, params = model
    rng = np.random.RandomState(1)
    prompts = [rng.randint(1, 96, size=120).tolist() for _ in range(3)]
    serve = ServeConfig(block_size=128, num_blocks=5, max_batch=3,
                        prefill_chunk=64, max_seq_len=256)
    eng = InferenceEngine(params, cfg, serve, record_events=True)
    reqs = [Request(p, max_new_tokens=16, arrival=float(i))
            for i, p in enumerate(prompts)]
    with _common.interpret_mode(True):
        stats = eng.run(reqs, deterministic=True)
    return {"prompts": prompts, "eng": eng, "stats": stats}


def test_eviction_fires_and_recovers(evict_run):
    st = evict_run["stats"]
    assert st["preemptions"] >= 1
    assert st["requests"] == 3
    evicted = [ev for ev in evict_run["eng"].events if ev[1] == "evict"]
    assert evicted, "no evict event recorded"
    # evicted sequences are readmitted and finish
    assert all(len(s.generated) == 16 for s in evict_run["eng"].finished)


def test_eviction_recompute_matches_greedy(model, evict_run):
    for i, p in enumerate(evict_run["prompts"]):
        got = [s for s in evict_run["eng"].finished
               if s.req.request_id == i][0].generated
        assert got == _greedy_ref(model, p, 16), f"request {i}"


def test_no_block_leaks_after_eviction(evict_run):
    assert evict_run["eng"].pool.used_blocks == 0


# -- host-side unit checks (no device work) ---------------------------------

def test_block_pool_invariants():
    pool = BlockPool(num_blocks=6, block_size=128)
    assert pool.free_blocks == 5          # block 0 reserved (null block)
    got = pool.alloc(5)
    assert got is not None and 0 not in got
    assert pool.alloc(1) is None          # all-or-nothing when dry
    pool.free(got[:2])
    assert pool.free_blocks == 2
    with pytest.raises(ValueError):
        pool.free([got[0]])               # double free
    with pytest.raises(ValueError):
        pool.free([0])                    # the null block is never owned
    assert pool.blocks_for(129) == 2
    assert 0.0 < pool.utilization < 1.0


def test_pad_table_pads_with_null_block():
    row = pad_table([3, 7], 4)
    assert row.dtype == np.int32
    assert row.tolist() == [3, 7, 0, 0]


def test_serve_config_and_submit_validation(model):
    cfg, params = model
    serve = ServeConfig(block_size=128, num_blocks=4, max_batch=4,
                        max_seq_len=256)
    assert serve.decode_buckets == (1, 2, 4)
    with pytest.raises(ValueError):
        ServeConfig(max_batch=4, decode_buckets=(1, 2))  # largest != max
    eng = InferenceEngine(params, cfg, serve)
    with pytest.raises(ValueError):
        eng.submit(Request([1] * 250, max_new_tokens=16))  # > max_seq_len
    with pytest.raises(ValueError):
        eng.submit(Request([]))


if __name__ == "__main__":
    pytest.main([__file__, "-q"])
