"""Continuous-batching serving engine (paddle_tpu/inference/engine.py).

Scheduler invariants pinned here:
  * token parity: continuous batching + chunked prefill + the paged KV
    cache produce the SAME greedy tokens as the contiguous-cache
    ``greedy_generate`` path, per request;
  * no block leaks: the pool returns to fully-free after every run,
    including runs with preemption;
  * deterministic replay: the same arrival trace replays to an
    identical event log and identical tokens;
  * preempt-by-eviction: when the pool runs dry mid-decode the
    youngest sequence is evicted, re-prefilled on readmission, and
    still produces the greedy reference tokens (recompute semantics).

Tiny model, pallas interpret mode on CPU. The two engine scenarios run
once in module fixtures; tests assert on their results.
"""
import json

import numpy as np
import pytest

import jax.numpy as jnp

from paddle_tpu.inference import (BlockPool, InferenceEngine, Request,
                                  ServeConfig, pad_table)
from paddle_tpu.models.llama import (greedy_generate, init_llama_params,
                                     llama_tiny)
from paddle_tpu.ops import _common


@pytest.fixture(autouse=True)
def _interpret():
    with _common.interpret_mode(True):
        yield


@pytest.fixture(scope="module")
def model():
    cfg = llama_tiny(vocab=96, hidden=64, layers=1, heads=4, kv_heads=2,
                     seq=512)
    return cfg, init_llama_params(cfg, seed=3)


def _greedy_ref(model, prompt, n_new):
    cfg, params = model
    with _common.interpret_mode(True):
        out = greedy_generate(params, jnp.asarray([prompt], jnp.int32), cfg,
                              n_new)
    return np.asarray(out)[0].tolist()


@pytest.fixture(scope="module")
def basic_run(model):
    """Two mixed-length prompts (one multi-chunk, multi-block) through
    the engine twice on the same deterministic trace."""
    cfg, params = model
    rng = np.random.RandomState(0)
    prompts = [rng.randint(1, 96, size=n).tolist() for n in (7, 130)]
    serve = ServeConfig(block_size=128, num_blocks=10, max_batch=2,
                        prefill_chunk=32, max_seq_len=512)

    def one():
        eng = InferenceEngine(params, cfg, serve, record_events=True)
        reqs = [Request(p, max_new_tokens=5, arrival=float(i))
                for i, p in enumerate(prompts)]
        stats = eng.run(reqs, deterministic=True)
        return eng, stats

    with _common.interpret_mode(True):
        eng, stats = one()
        eng2, _ = one()
    return {"prompts": prompts, "eng": eng, "stats": stats, "eng2": eng2}


def test_engine_matches_greedy_generate(model, basic_run):
    for i, p in enumerate(basic_run["prompts"]):
        got = [s for s in basic_run["eng"].finished
               if s.req.request_id == i][0].generated
        assert got == _greedy_ref(model, p, 5), f"request {i}"


def test_no_block_leaks(basic_run):
    eng = basic_run["eng"]
    assert eng.pool.used_blocks == 0
    assert eng.pool.free_blocks == eng.serve.num_blocks - 1


def test_deterministic_replay(basic_run):
    eng, eng2 = basic_run["eng"], basic_run["eng2"]
    assert eng.events == eng2.events
    toks = lambda e: {s.req.request_id: s.tokens for s in e.finished}
    assert toks(eng) == toks(eng2)


def test_bounded_compiles(basic_run):
    """One compile per bucketed shape: prefill chunk + decode buckets."""
    stats = basic_run["stats"]
    assert set(stats["compiles"]) <= {"prefill_32", "decode_1", "decode_2"}


def test_bounded_compiles_speculative(model):
    """With speculation on (PR 18) the family stays counted/bounded:
    the draft's prefill + per-bucket decode programs and the base's
    per-bucket K+1-wide verify program replace plain decode — no
    program keyed on data (accept length, proposal count) ever
    compiles."""
    cfg, params = model
    rng = np.random.RandomState(0)
    prompts = [rng.randint(1, 96, size=n).tolist() for n in (7, 40)]
    serve = ServeConfig(block_size=128, num_blocks=10, max_batch=2,
                        prefill_chunk=32, max_seq_len=512,
                        speculative=True, draft_k=2)
    eng = InferenceEngine(params, cfg, serve)
    reqs = [Request(p, max_new_tokens=5, arrival=float(i))
            for i, p in enumerate(prompts)]
    stats = eng.run(reqs, deterministic=True)
    assert set(stats["compiles"]) <= {"prefill_32", "draft_prefill_32",
                                      "draft_1", "draft_2",
                                      "verify_1", "verify_2"}
    assert any(k.startswith("verify_") for k in stats["compiles"])
    for i, p in enumerate(prompts):
        got = [s for s in eng.finished
               if s.req.request_id == i][0].generated
        assert got == _greedy_ref(model, p, 5), f"request {i}"


@pytest.fixture(scope="module")
def evict_run(model):
    """Pool sized so three one-block sequences admit, then starve when
    each crosses its block boundary mid-decode: 4 usable blocks, three
    120-token prompts growing past 128 cached tokens."""
    cfg, params = model
    rng = np.random.RandomState(1)
    prompts = [rng.randint(1, 96, size=120).tolist() for _ in range(3)]
    serve = ServeConfig(block_size=128, num_blocks=5, max_batch=3,
                        prefill_chunk=64, max_seq_len=256)
    eng = InferenceEngine(params, cfg, serve, record_events=True)
    reqs = [Request(p, max_new_tokens=16, arrival=float(i))
            for i, p in enumerate(prompts)]
    with _common.interpret_mode(True):
        stats = eng.run(reqs, deterministic=True)
    return {"prompts": prompts, "eng": eng, "stats": stats}


def test_eviction_fires_and_recovers(evict_run):
    st = evict_run["stats"]
    assert st["preemptions"] >= 1
    assert st["requests"] == 3
    evicted = [ev for ev in evict_run["eng"].events if ev[1] == "evict"]
    assert evicted, "no evict event recorded"
    # evicted sequences are readmitted and finish
    assert all(len(s.generated) == 16 for s in evict_run["eng"].finished)


def test_eviction_recompute_matches_greedy(model, evict_run):
    for i, p in enumerate(evict_run["prompts"]):
        got = [s for s in evict_run["eng"].finished
               if s.req.request_id == i][0].generated
        assert got == _greedy_ref(model, p, 16), f"request {i}"


def test_no_block_leaks_after_eviction(evict_run):
    assert evict_run["eng"].pool.used_blocks == 0


# -- host-side unit checks (no device work) ---------------------------------

def test_block_pool_invariants():
    pool = BlockPool(num_blocks=6, block_size=128)
    assert pool.free_blocks == 5          # block 0 reserved (null block)
    got = pool.alloc(5)
    assert got is not None and 0 not in got
    assert pool.alloc(1) is None          # all-or-nothing when dry
    pool.free(got[:2])
    assert pool.free_blocks == 2
    with pytest.raises(ValueError):
        pool.free([got[0]])               # double free
    with pytest.raises(ValueError):
        pool.free([0])                    # the null block is never owned
    assert pool.blocks_for(129) == 2
    assert 0.0 < pool.utilization < 1.0


def test_pad_table_pads_with_null_block():
    row = pad_table([3, 7], 4)
    assert row.dtype == np.int32
    assert row.tolist() == [3, 7, 0, 0]


def test_serve_config_and_submit_validation(model):
    cfg, params = model
    serve = ServeConfig(block_size=128, num_blocks=4, max_batch=4,
                        max_seq_len=256)
    assert serve.decode_buckets == (1, 2, 4)
    with pytest.raises(ValueError):
        ServeConfig(max_batch=4, decode_buckets=(1, 2))  # largest != max
    eng = InferenceEngine(params, cfg, serve)
    with pytest.raises(ValueError):
        eng.submit(Request([1] * 250, max_new_tokens=16))  # > max_seq_len
    with pytest.raises(ValueError):
        eng.submit(Request([]))


if __name__ == "__main__":
    pytest.main([__file__, "-q"])


# -- PR-12: request tracing, streaming SLO, flight recorder -------------------

_BUCKET = 10.0 ** (1.0 / 16.0) * (1.0 + 1e-9)  # one histogram bucket


def _nearest_rank(xs, q):
    import math
    s = sorted(xs)
    return s[max(0, math.ceil(q / 100.0 * len(s)) - 1)]


@pytest.fixture(scope="module")
def traced_evict_run(model):
    """The evict_run trace replayed with every observability layer on —
    tracing must not perturb scheduling, so tokens and the event log must
    match the untraced fixture bit for bit."""
    cfg, params = model
    rng = np.random.RandomState(1)
    prompts = [rng.randint(1, 96, size=120).tolist() for _ in range(3)]
    serve = ServeConfig(block_size=128, num_blocks=5, max_batch=3,
                        prefill_chunk=64, max_seq_len=256)
    eng = InferenceEngine(params, cfg, serve, record_events=True,
                          trace_requests=True, flight_recorder=True)
    reqs = [Request(p, max_new_tokens=16, arrival=float(i))
            for i, p in enumerate(prompts)]
    with _common.interpret_mode(True):
        stats = eng.run(reqs, deterministic=True)
    return {"eng": eng, "stats": stats}


def test_tracing_is_measurement_only(evict_run, traced_evict_run):
    """Bit-identical tokens and event log, traced vs untraced."""
    toks = lambda e: {s.req.request_id: s.tokens for s in e.finished}
    assert toks(traced_evict_run["eng"]) == toks(evict_run["eng"])
    assert traced_evict_run["eng"].events == evict_run["eng"].events


def test_span_tree_spans_eviction_and_reprefill(traced_evict_run):
    from paddle_tpu.observability.request_trace import spans_overlap
    eng, stats = traced_evict_run["eng"], traced_evict_run["stats"]
    assert stats["preemptions"] >= 1
    assert eng.tracer.request_ids() == [0, 1, 2]
    evicted = [rid for rid in (0, 1, 2)
               if any(s["cat"] == "evict"
                      for s in eng.tracer.tree(rid)["children"])]
    assert evicted, "eviction run recorded no evict spans"
    tree = eng.tracer.tree(evicted[0])
    cats = [c["cat"] for c in tree["children"]]
    names = [c["name"] for c in tree["children"]]
    # full lifecycle: queue wait -> prefill -> decode -> evicted ->
    # requeued -> recompute prefill -> decode again -> finish
    for cat in ("queue", "prefill", "decode", "evict", "reprefill",
                "finish"):
        assert cat in cats, (cat, cats)
    assert "requeue" in names
    assert cats.index("evict") < cats.index("reprefill")
    # recompute covers already-generated context, after the evict marker
    re_i = cats.index("reprefill")
    assert tree["children"][re_i]["args"]["n_tokens"] > 0
    # children are time-ordered under a root covering the lifetime
    t0s = [c["t0"] for c in tree["children"]]
    assert t0s == sorted(t0s)
    assert tree["t0"] <= t0s[0] and tree["t1"] >= tree["children"][-1]["t1"]
    # a request is in one engine phase at a time: row spans never overlap
    assert not spans_overlap(tree["children"])


def test_streaming_slo_within_one_bucket_of_exact(traced_evict_run):
    eng, stats = traced_evict_run["eng"], traced_evict_run["stats"]
    ttfts = [s.first_token_t - s.arrival for s in eng.finished]
    gaps = []
    for s in eng.finished:
        gaps.extend(np.diff(s.token_times).tolist())
    for key, xs, q in (("ttft_stream_p50_s", ttfts, 50),
                       ("ttft_stream_p99_s", ttfts, 99),
                       ("tpot_stream_p50_s", gaps, 50),
                       ("tpot_stream_p99_s", gaps, 99)):
        exact = _nearest_rank(xs, q)
        assert exact / _BUCKET <= stats[key] <= exact * _BUCKET, (key, exact,
                                                                 stats[key])
    # queue-wait histogram saw exactly one first admission per request
    assert eng.slo["queue_wait"].count == 3


def test_trace_exports_jsonl_and_chrome(traced_evict_run, tmp_path):
    eng = traced_evict_run["eng"]
    jp = eng.tracer.export_jsonl(str(tmp_path / "spans.jsonl"))
    from paddle_tpu.observability import load_jsonl
    recs = load_jsonl(jp)
    assert len(recs) == eng.tracer.span_count()
    assert all(r["t0_s"] >= 0 for r in recs)
    cp = eng.tracer.export_chrome(str(tmp_path / "trace.json"))
    data = json.load(open(cp))
    names = {e["args"]["name"] for e in data["traceEvents"]
             if e["name"] == "thread_name"}
    assert {"engine/admit", "engine/prefill", "engine/decode",
            "request 0", "request 1", "request 2"} <= names
    evs = [e for e in data["traceEvents"] if e["ph"] == "X"]
    assert evs and all(e["dur"] >= 0 for e in evs)


def test_metrics_snapshot_and_prometheus(traced_evict_run):
    eng = traced_evict_run["eng"]
    snap = eng.metrics_snapshot()
    assert snap["finished_requests"] == 3
    assert snap["queue_depth"] == 0 and snap["pool_utilization"] == 0.0
    prom = eng.render_prometheus()
    assert "# TYPE paddle_tpu_serve_ttft_seconds histogram" in prom
    assert "paddle_tpu_serve_tpot_seconds_bucket" in prom
    assert 'le="+Inf"' in prom
    assert "paddle_tpu_serve_preemptions" in prom
    assert f"paddle_tpu_serve_queue_wait_seconds_count 3" in prom


def test_recorder_ring_populated_and_clean(traced_evict_run):
    eng = traced_evict_run["eng"]
    assert len(eng.recorder.ring) > 0
    assert eng.recorder.dumped == []
    rec = next(r for r in reversed(eng.recorder.ring) if "tokens" in r)
    assert {"iteration", "queue_depth", "pool_utilization"} <= set(rec)


def test_unfinished_requests_counted_not_dropped(model):
    """End-of-run TTFT accounting: a request that never produced a first
    token lands in ``unfinished`` instead of silently vanishing from (or
    poisoning) the percentiles."""
    cfg, params = model
    rng = np.random.RandomState(0)
    prompts = [rng.randint(1, 96, size=n).tolist() for n in (7, 130)]
    serve = ServeConfig(block_size=128, num_blocks=10, max_batch=2,
                        prefill_chunk=32, max_seq_len=512)
    eng = InferenceEngine(params, cfg, serve)
    reqs = [Request(p, max_new_tokens=5, arrival=0.0) for p in prompts]
    with pytest.raises(RuntimeError):
        eng.run(reqs, deterministic=True, max_iterations=8)
    st = eng.stats()
    assert st["requests"] + st["unfinished"] == 2
    assert st["unfinished"] >= 1
    # percentiles are conditioned on requests that got a first token
    n_with_token = sum(1 for s in eng.finished
                       if s.first_token_t is not None)
    assert (st["ttft_p50_s"] is None) == (n_with_token == 0)
    # a finished run reports zero unfinished (see traced_evict_run)


def test_exception_dumps_flight_recorder(model, tmp_path, monkeypatch):
    """A mid-serve crash writes the last-N-iterations post-mortem before
    the exception propagates."""
    from paddle_tpu.observability import load_dump
    monkeypatch.setenv("PADDLE_TPU_TELEMETRY_DIR", str(tmp_path))
    cfg, params = model
    rng = np.random.RandomState(0)
    prompts = [rng.randint(1, 96, size=n).tolist() for n in (7, 130)]
    serve = ServeConfig(block_size=128, num_blocks=10, max_batch=2,
                        prefill_chunk=32, max_seq_len=512)
    eng = InferenceEngine(params, cfg, serve, flight_recorder=True)
    reqs = [Request(p, max_new_tokens=5, arrival=0.0) for p in prompts]
    with pytest.raises(RuntimeError):
        eng.run(reqs, deterministic=True, max_iterations=6)
    assert len(eng.recorder.dumped) == 1
    payload = load_dump(eng.recorder.dumped[0])
    assert payload["reason"] == "exception"
    assert payload["source"] == "engine"
    assert payload["n_records"] > 0
    assert payload["records"][-1]["iteration"] == 6
