"""Overload-hardened serving (PR 14): admission control, deadlines &
load shedding, graceful degradation.

The degradation contract pinned here (also PARITY.md):
  * submit() never queues unboundedly — overload is a deterministic
    Admission outcome (queue_full / overcommit / rate_limit), never an
    exception and never silent;
  * shedding is deterministic: replaying an arrival trace sheds the
    SAME set of requests and the survivors' token streams are
    bit-identical (and match the greedy reference);
  * every request the engine saw ends finished/rejected/shed/failed
    with a cause (outcomes());
  * a 2x capacity burst leaves a leak-free pool;
  * eviction is priority-aware; a prefill chunk shrinks its live span
    (same compiled shape) before the scheduler resorts to eviction.

Tiny model, pallas interpret mode on CPU.
"""
import numpy as np
import pytest

import jax.numpy as jnp

from paddle_tpu.inference import (Admission, InferenceEngine, Request,
                                  ServeConfig)
from paddle_tpu.models.llama import (greedy_generate, init_llama_params,
                                     llama_tiny)
from paddle_tpu.ops import _common


@pytest.fixture(autouse=True)
def _interpret():
    with _common.interpret_mode(True):
        yield


@pytest.fixture(scope="module")
def model():
    cfg = llama_tiny(vocab=96, hidden=64, layers=1, heads=4, kv_heads=2,
                     seq=512)
    return cfg, init_llama_params(cfg, seed=3)


def _greedy_ref(model, prompt, n_new):
    cfg, params = model
    with _common.interpret_mode(True):
        out = greedy_generate(params, jnp.asarray([prompt], jnp.int32),
                              cfg, n_new)
    return np.asarray(out)[0].tolist()


def _prompts(n, size=20, seed=0):
    rng = np.random.RandomState(seed)
    return [rng.randint(1, 96, size=size).tolist() for _ in range(n)]


# -- admission valves (host-side, no device work needed) ---------------------


def test_bounded_queue_rejects_with_cause(model):
    cfg, params = model
    serve = ServeConfig(block_size=128, num_blocks=10, max_batch=2,
                        max_seq_len=256, max_queue=2)
    eng = InferenceEngine(params, cfg, serve)
    outs = [eng.submit(Request(p, max_new_tokens=4))
            for p in _prompts(4)]
    assert [o.accepted for o in outs] == [True, True, False, False]
    assert all(isinstance(o, Admission) for o in outs)
    assert [o.cause for o in outs] == [None, None, "queue_full",
                                       "queue_full"]
    assert len(eng.waiting) == 2 and len(eng.rejected) == 2
    # rejected requests carry a terminal outcome — nothing silent
    assert eng.outcomes()[outs[2].request_id] == ("rejected", "queue_full")


def test_overcommit_rejects_on_block_demand(model):
    cfg, params = model
    # 3 usable blocks, overcommit 1.0: worst-case demand must stay <= 3
    serve = ServeConfig(block_size=128, num_blocks=4, max_batch=2,
                        max_seq_len=384, overcommit=1.0, max_queue=16)
    eng = InferenceEngine(params, cfg, serve)
    a = eng.submit(Request([1] * 200, max_new_tokens=4))   # 2 blocks
    b = eng.submit(Request([1] * 100, max_new_tokens=4))   # 1 block
    c = eng.submit(Request([1] * 10, max_new_tokens=4))    # 1 over budget
    assert a.accepted and b.accepted
    assert not c.accepted and c.cause == "overcommit"


def test_rate_limit_token_bucket_on_engine_clock(model):
    cfg, params = model
    serve = ServeConfig(block_size=128, num_blocks=16, max_batch=2,
                        max_seq_len=256, rate_limit=0.5, burst=2,
                        max_queue=64)
    eng = InferenceEngine(params, cfg, serve)
    burst = [eng.submit(Request(p, max_new_tokens=2))
             for p in _prompts(3, size=8)]
    assert [o.accepted for o in burst] == [True, True, False]
    assert burst[2].cause == "rate_limit"
    # advance the engine clock 2 units -> one refill at rate 0.5
    eng._clock = 2.0
    again = [eng.submit(Request(p, max_new_tokens=2))
             for p in _prompts(2, size=8, seed=1)]
    assert [o.accepted for o in again] == [True, False]


def test_env_knobs_drive_admission(model, monkeypatch):
    cfg, params = model
    monkeypatch.setenv("PADDLE_TPU_SERVE_MAX_QUEUE", "1")
    serve = ServeConfig(block_size=128, num_blocks=10, max_batch=2,
                        max_seq_len=256)
    eng = InferenceEngine(params, cfg, serve)
    assert eng.admission.max_queue == 1
    assert eng.submit(Request([1] * 8, max_new_tokens=2)).accepted
    assert eng.submit(Request([2] * 8,
                              max_new_tokens=2)).cause == "queue_full"
    # explicit ServeConfig field wins over the env
    eng2 = InferenceEngine(params, cfg, ServeConfig(
        block_size=128, num_blocks=10, max_batch=2, max_seq_len=256,
        max_queue=7))
    assert eng2.admission.max_queue == 7


def test_malformed_requests_still_raise(model):
    cfg, params = model
    serve = ServeConfig(block_size=128, num_blocks=4, max_batch=4,
                        max_seq_len=256)
    eng = InferenceEngine(params, cfg, serve)
    with pytest.raises(ValueError):
        eng.submit(Request([1] * 300, max_new_tokens=16))
    with pytest.raises(ValueError):
        eng.submit(Request([]))


# -- deadlines & shedding -----------------------------------------------------


def _overload_run(model, seed=0):
    """A 2x-capacity deterministic burst: a tiny pool + max_batch 1, six
    requests arriving faster than the engine can serve, tight TTFT
    deadlines — some must shed."""
    cfg, params = model
    rng = np.random.RandomState(seed)
    prompts = [rng.randint(1, 96, size=30).tolist() for _ in range(6)]
    serve = ServeConfig(block_size=128, num_blocks=3, max_batch=1,
                        prefill_chunk=32, max_seq_len=256, max_queue=8,
                        overcommit=8.0)
    eng = InferenceEngine(params, cfg, serve, record_events=True)
    reqs = [Request(p, max_new_tokens=6, arrival=float(i),
                    ttft_deadline=10.0, deadline=40.0)
            for i, p in enumerate(prompts)]
    stats = eng.run(reqs, deterministic=True)
    return eng, stats, prompts


@pytest.fixture(scope="module")
def overload_runs(model):
    with _common.interpret_mode(True):
        a = _overload_run(model)
        b = _overload_run(model)
    return a, b


def test_deadline_shedding_fires(overload_runs):
    (eng, stats, _), _ = overload_runs
    assert stats["shed"] >= 1, "overload trace must shed"
    assert stats["requests"] >= 1, "some requests must still finish"
    for seq in eng.shed:
        assert seq.fail_cause in ("ttft_deadline", "deadline")


def test_shedding_is_deterministic_across_replays(overload_runs):
    (eng_a, _, _), (eng_b, _, _) = overload_runs
    shed = lambda e: sorted((s.req.request_id, s.fail_cause)
                            for s in e.shed)
    assert shed(eng_a) == shed(eng_b)
    assert shed(eng_a), "expected a non-empty shed set"
    toks = lambda e: {s.req.request_id: s.tokens for s in e.finished}
    assert toks(eng_a) == toks(eng_b)
    assert eng_a.events == eng_b.events


def test_survivors_match_greedy_reference(model, overload_runs):
    (eng, _, prompts), _ = overload_runs
    assert eng.finished, "no survivors"
    for seq in eng.finished:
        ref = _greedy_ref(model, prompts[seq.req.request_id], 6)
        assert seq.generated == ref, f"request {seq.req.request_id}"


def test_no_leaks_and_no_silent_drops_after_burst(overload_runs):
    (eng, stats, prompts), _ = overload_runs
    assert eng.pool.used_blocks == 0
    outcomes = stats["outcomes"]
    assert set(outcomes) == set(range(len(prompts)))
    for rid, (state, cause) in outcomes.items():
        assert state in ("finished", "shed", "rejected", "failed"), (
            rid, state)
        if state != "finished":
            assert cause, f"request {rid}: terminal state without a cause"


def test_shed_events_reach_observability(model):
    cfg, params = model
    rng = np.random.RandomState(2)
    prompts = [rng.randint(1, 96, size=30).tolist() for _ in range(4)]
    serve = ServeConfig(block_size=128, num_blocks=3, max_batch=1,
                        prefill_chunk=32, max_seq_len=256, max_queue=8,
                        overcommit=8.0)
    eng = InferenceEngine(params, cfg, serve, record_events=True,
                          trace_requests=True, flight_recorder=True)
    reqs = [Request(p, max_new_tokens=6, arrival=float(i),
                    ttft_deadline=6.0)
            for i, p in enumerate(prompts)]
    with _common.interpret_mode(True):
        stats = eng.run(reqs, deterministic=True)
    assert stats["shed"] >= 1
    shed_rids = {s.req.request_id for s in eng.shed}
    # tracer: one shed span per shed request, closing its queue wait
    assert eng.tracer.span_count("shed") == len(shed_rids)
    # flight recorder: a shed record per event
    recorded = [r for r in eng.recorder.ring
                if r.get("event") == "shed"]
    assert {r["rid"] for r in recorded} == shed_rids
    # prometheus: the scalar counter renders
    assert "paddle_tpu_serve_shed_requests" in eng.render_prometheus()


# -- graceful degradation under pool pressure --------------------------------


def test_eviction_is_priority_aware(model):
    """Two decoders + forced pressure: the LOW-priority one is evicted
    even though it is older (pre-PR-14 tie-break was youngest-first)."""
    cfg, params = model
    rng = np.random.RandomState(3)
    serve = ServeConfig(block_size=128, num_blocks=6, max_batch=2,
                        prefill_chunk=32, max_seq_len=256)
    eng = InferenceEngine(params, cfg, serve, record_events=True)
    lo = Request(rng.randint(1, 96, size=8).tolist(), max_new_tokens=8,
                 priority=0)
    hi = Request(rng.randint(1, 96, size=8).tolist(), max_new_tokens=8,
                 priority=5)
    with _common.interpret_mode(True):
        assert eng.submit(lo).accepted and eng.submit(hi).accepted
        while any(s.state != "running" for s in eng.active) \
                or len(eng.active) < 2:
            eng.step()
        assert eng._evict_one()
    assert eng.waiting and eng.waiting[0].req.request_id == lo.request_id
    assert all(s.req.request_id == hi.request_id for s in eng.active)


def test_prefill_shrinks_before_evicting(model):
    """Steal most of the pool mid-prefill: the next chunk must shrink its
    live span to the remaining headroom (same compiled shape, no
    eviction) and the request must still match the greedy reference."""
    cfg, params = model
    rng = np.random.RandomState(4)
    prompt = rng.randint(1, 96, size=400).tolist()
    serve = ServeConfig(block_size=128, num_blocks=7, max_batch=1,
                        prefill_chunk=256, max_seq_len=512)
    eng = InferenceEngine(params, cfg, serve, record_events=True)
    with _common.interpret_mode(True):
        assert eng.submit(Request(prompt, max_new_tokens=4)).accepted
        eng.step()                       # chunk 1: 256 tokens, 2 blocks
        assert eng.active[0].n_cached == 256
        stolen = eng.pool.alloc(3)       # leave exactly 1 free block
        assert stolen is not None and eng.pool.free_blocks == 1
        eng.step()                       # chunk 2 shrinks 144 -> 128
        assert eng.active[0].n_cached == 256 + 128
        eng.pool.free(stolen)
        stats = eng.run([], deterministic=True)
    shrunk = [ev for ev in eng.events if ev[1] == "prefill_shrink"]
    assert shrunk and shrunk[0][3] == 128
    assert stats["preemptions"] == 0, "shrink must pre-empt eviction"
    assert stats["compiles"].keys() <= {"prefill_256", "decode_1"}
    seq = eng.finished[0]
    assert seq.generated == _greedy_ref(model, prompt, 4)
    assert eng.pool.used_blocks == 0


# -- BlockPool hardening ------------------------------------------------------


def test_block_pool_named_errors():
    """Corrupting frees fail loudly with BlockPoolError (a ValueError,
    so pre-PR-14 handlers keep working) and leave the pool UNCHANGED —
    validation is atomic, no partial free."""
    from paddle_tpu.inference import BlockPool, BlockPoolError
    pool = BlockPool(num_blocks=8, block_size=128)
    blocks = pool.alloc(3)
    free_before = pool.free_blocks

    with pytest.raises(BlockPoolError, match="null block 0"):
        pool.free([0])
    with pytest.raises(BlockPoolError, match="out-of-range"):
        pool.free([8])
    with pytest.raises(BlockPoolError, match="out-of-range"):
        pool.free([-1])
    pool.free([blocks[0]])
    with pytest.raises(BlockPoolError, match="double free"):
        pool.free([blocks[0]])
    # duplicates WITHIN one call are a double free too
    with pytest.raises(BlockPoolError, match="double free"):
        pool.free([blocks[1], blocks[1]])
    # a rejected free touched nothing: the valid id in the bad batch is
    # still allocated and frees cleanly now
    assert pool.free_blocks == free_before + 1
    pool.free(blocks[1:])
    assert pool.used_blocks == 0
    assert issubclass(BlockPoolError, ValueError)


if __name__ == "__main__":
    pytest.main([__file__, "-q"])
