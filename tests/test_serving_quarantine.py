"""Poison-request quarantine (PR 14): a request whose prefill/decode
raises — or produces non-finite logits — is quarantined (blocks
released, marked failed with a cause) while the engine keeps serving
everyone else. Decode poison re-drives the surviving batch rows in the
same iteration.

Injection uses testing/faults.py poison points (INSIDE the engine's
quarantine try blocks — contrast the crash-matrix points exercised by
test_engine_journal.py, which kill the engine). Genuine-NaN paths are
exercised with params surgery: embedding row 95 is set to NaN, so any
prompt/history containing token 95 poisons its own logits.
"""
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.inference import (InferenceEngine, PoisonError, Request,
                                  ServeConfig)
from paddle_tpu.models.llama import (greedy_generate, init_llama_params,
                                     llama_tiny)
from paddle_tpu.ops import _common
from paddle_tpu.testing import faults


@pytest.fixture(autouse=True)
def _interpret(monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_FAULTS", "1")
    with _common.interpret_mode(True):
        yield
    faults.disarm()


@pytest.fixture(scope="module")
def model():
    cfg = llama_tiny(vocab=96, hidden=64, layers=1, heads=4, kv_heads=2,
                     seq=512)
    return cfg, init_llama_params(cfg, seed=3)


@pytest.fixture(scope="module")
def nan_model(model):
    """Same model with a NaN embedding row for token 95: feeding 95
    through the network yields non-finite logits — a genuine poison
    input, not an injected exception."""
    cfg, params = model
    import jax
    params = jax.tree_util.tree_map(lambda x: x, params)  # shallow copy

    def _poison(tree):
        out = {}
        for k, v in tree.items():
            if isinstance(v, dict):
                out[k] = _poison(v)
            elif k == "embed":
                out[k] = v.at[95].set(jnp.nan)
            else:
                out[k] = v
        return out

    return cfg, _poison(params)


def _greedy_ref(model, prompt, n_new):
    cfg, params = model
    with _common.interpret_mode(True):
        out = greedy_generate(params, jnp.asarray([prompt], jnp.int32),
                              cfg, n_new)
    return np.asarray(out)[0].tolist()


def _serve(model, **kw):
    cfg, params = model
    serve = ServeConfig(block_size=128, num_blocks=10, max_batch=2,
                        prefill_chunk=32, max_seq_len=256, **kw)
    return InferenceEngine(params, cfg, serve, record_events=True)


def _prompts(n, size=20, seed=0, hi=95):
    rng = np.random.RandomState(seed)
    return [rng.randint(1, hi, size=size).tolist() for _ in range(n)]


# -- prefill quarantine -------------------------------------------------------


def test_prefill_exception_quarantines_one_request(model):
    """First request's prefill kernel raises; it is failed with a cause
    and released, the second request still finishes with reference
    tokens, and the pool ends clean."""
    eng = _serve(model)
    p_bad, p_ok = _prompts(2, size=24)
    with faults.scope("serve.prefill.poison", "raise", nth=1):
        stats = eng.run([Request(p_bad, max_new_tokens=4),
                         Request(p_ok, max_new_tokens=4)],
                        deterministic=True)
    assert stats["failed"] == 1 and stats["requests"] == 1
    bad = eng.failed[0]
    assert bad.req.request_id == 0 and "prefill" in bad.fail_cause
    assert bad.blocks == [] and eng.pool.used_blocks == 0
    ok = eng.finished[0]
    assert ok.generated == _greedy_ref(model, p_ok, 4)
    assert stats["outcomes"][0][0] == "failed"


def test_prefill_nan_logits_quarantined(nan_model, model):
    """A prompt containing the NaN-embedded token yields non-finite
    prefill logits -> quarantined by the nan screen; a clean prompt on
    the same engine finishes and matches the NaN-free reference (token
    95 never appears in its prompt or output)."""
    eng = _serve(nan_model)
    p_bad = _prompts(1, size=24, seed=1)[0]
    p_bad[10] = 95                      # the poisoned embedding row
    p_ok = _prompts(1, size=24, seed=2)[0]
    stats = eng.run([Request(p_bad, max_new_tokens=4),
                     Request(p_ok, max_new_tokens=4)],
                    deterministic=True)
    assert stats["failed"] == 1
    assert eng.failed[0].fail_cause == "non-finite prefill logits"
    assert eng.pool.used_blocks == 0
    ref = _greedy_ref(nan_model, p_ok, 4)
    assert eng.finished[0].generated == ref
    assert 95 not in ref


def test_nan_check_can_be_disabled(nan_model):
    """nan_check=False skips the logits screen: the poisoned request is
    NOT quarantined (it keeps decoding garbage argmax tokens)."""
    cfg, params = nan_model
    serve = ServeConfig(block_size=128, num_blocks=10, max_batch=2,
                        prefill_chunk=32, max_seq_len=256,
                        nan_check=False)
    eng = InferenceEngine(params, cfg, serve)
    p_bad = _prompts(1, size=24, seed=1)[0]
    p_bad[10] = 95
    stats = eng.run([Request(p_bad, max_new_tokens=2)],
                    deterministic=True)
    assert stats["failed"] == 0 and stats["requests"] == 1


# -- decode quarantine & re-drive ---------------------------------------------


def test_decode_nan_row_quarantined_batchmate_survives(nan_model):
    """Two decoders; one's first generated token is forced to the NaN
    embedding row, so its NEXT decode step produces a non-finite logits
    row. Only that row is quarantined — its batchmate's stream is
    bit-identical to a solo run."""
    cfg, params = nan_model
    p_bad, p_ok = _prompts(2, size=24, seed=3)
    solo = _serve(nan_model)
    solo_stats = solo.run([Request(p_ok, max_new_tokens=6)],
                          deterministic=True)
    assert solo_stats["requests"] == 1
    ref = solo.finished[0].tokens

    eng = _serve(nan_model)
    assert eng.submit(Request(p_bad, max_new_tokens=6)).accepted
    assert eng.submit(Request(p_ok, max_new_tokens=6)).accepted
    # drive both through prefill + first decode
    while len(eng.active) < 2 or not all(s.generated for s in eng.active):
        eng.step()
    bad = next(s for s in eng.active if s.req.request_id == 0)
    bad.tokens[-1] = 95                 # force the poison row into history
    stats = eng.run([], deterministic=True)
    assert stats["failed"] == 1
    assert eng.failed[0].req.request_id == 0
    assert eng.failed[0].fail_cause == "non-finite decode logits"
    assert eng.finished[0].tokens == ref
    assert eng.pool.used_blocks == 0


def test_decode_poison_error_redrives_batch(model):
    """A corrupt-action callable raises PoisonError(rid) from inside the
    decode batch: the engine quarantines that row and RE-DRIVES the
    remaining rows in the same iteration — the survivor finishes with
    reference tokens and stats count the re-drive."""
    eng = _serve(model)
    p_bad, p_ok = _prompts(2, size=24, seed=4)

    def boom(ctx):
        raise PoisonError(ctx["rids"][0], "injected decode poison")

    with faults.scope("serve.decode.poison", "corrupt", nth=2,
                      corrupt=boom):
        stats = eng.run([Request(p_bad, max_new_tokens=6),
                         Request(p_ok, max_new_tokens=6)],
                        deterministic=True)
    assert stats["failed"] == 1 and stats["requests"] == 1
    assert stats["decode_redrives"] >= 1
    assert eng.failed[0].fail_cause == "injected decode poison"
    assert eng.finished[0].generated == _greedy_ref(model, p_ok, 6)
    assert eng.pool.used_blocks == 0


def test_decode_generic_exception_still_raises(model):
    """A NON-poison decode failure (no request attribution) must not be
    swallowed by quarantine — it propagates, and run()'s crash path
    releases every live block (satellite: leak-free pool after crash)."""
    eng = _serve(model)
    with faults.scope("serve.decode.poison", "raise", nth=2):
        with pytest.raises(faults.FaultError):
            eng.run([Request(p, max_new_tokens=6)
                     for p in _prompts(2, size=24, seed=5)],
                    deterministic=True)
    assert eng.pool.used_blocks == 0
    assert not eng.active and eng.waiting   # crashed work is re-queued


def test_quarantine_reaches_observability(model):
    cfg, params = model
    serve = ServeConfig(block_size=128, num_blocks=10, max_batch=2,
                        prefill_chunk=32, max_seq_len=256)
    eng = InferenceEngine(params, cfg, serve, record_events=True,
                          trace_requests=True, flight_recorder=True)
    p_bad, p_ok = _prompts(2, size=24, seed=6)
    with faults.scope("serve.prefill.poison", "raise", nth=1):
        eng.run([Request(p_bad, max_new_tokens=3),
                 Request(p_ok, max_new_tokens=3)], deterministic=True)
    assert eng.tracer.span_count("quarantine") == 1
    assert any(r.get("event") == "quarantine" and r.get("rid") == 0
               for r in eng.recorder.ring)
    assert "paddle_tpu_serve_failed_requests 1" in eng.render_prometheus()


if __name__ == "__main__":
    pytest.main([__file__, "-q"])
