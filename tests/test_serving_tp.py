"""Tensor-parallel serving (PR 19).

The contract under test is PARITY.md's: an engine running at mp > 1
inside the ('mp',)-sharded mesh — weights sliced per param_pspecs,
KV/scale/draft pools sharded by kv-head — emits token streams that are
bitwise-identical to the same trace at mp=1. Greedy argmax absorbs the
ULP-level reassociation drift of the row-parallel o_proj/down_proj
reductions, and the verify step all-gathers full-vocab logits in-island
so accept/commit decisions are rank-identical by construction.

Covered here: stream parity (plain / int8+prefix / speculative / under
eviction), the sharded mid-serve weight swap (drain, zero drops, swap
lands on sharded leaves), per-rank pool accounting, divisibility
rejection at init, and the full PR-14 crash matrix re-run on a sharded
engine with speculation + int8 + prefix caching all on.
"""
import numpy as np
import pytest

from paddle_tpu.inference import (InferenceEngine, Request, ServeConfig,
                                  read_journal)
from paddle_tpu.models.llama import init_llama_params, llama_tiny
from paddle_tpu.ops import _common
from paddle_tpu.testing import faults


@pytest.fixture(autouse=True)
def _interpret(monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_FAULTS", "1")
    with _common.interpret_mode(True):
        yield
    faults.disarm()


@pytest.fixture(scope="module")
def model():
    # two layers so the default draft (first layer only) genuinely
    # disagrees with the base model, and so the later-layer KV pools
    # see the hidden-state drift the parity contract has to absorb
    cfg = llama_tiny(vocab=96, hidden=64, layers=2, heads=4, kv_heads=2,
                     seq=512)
    return cfg, init_llama_params(cfg, seed=3)


def _requests(n=3, max_new=8, seed=11):
    rng = np.random.RandomState(seed)
    # one multi-block prompt (130 > block_size) so the sharded pools
    # cross block boundaries mid-trace
    return [Request(rng.randint(1, 90, size=sz).tolist(),
                    max_new_tokens=max_new, arrival=float(i),
                    request_id=i)
            for i, sz in enumerate([9, 40, 130][:n])]


def _run(model, reqs=None, journal=None, engine_kw=None, **kw):
    cfg, params = model
    serve = ServeConfig(block_size=128, num_blocks=kw.pop("num_blocks", 10),
                        max_batch=2, prefill_chunk=32, max_seq_len=256,
                        **kw)
    eng = InferenceEngine(params, cfg, serve, record_events=True,
                          journal=journal, **(engine_kw or {}))
    eng.run(reqs if reqs is not None else _requests(), deterministic=True)
    return {s.req.request_id: s.generated for s in eng.finished}, eng


# -- stream parity ------------------------------------------------------------

COMBOS = [
    pytest.param({}, id="plain"),
    pytest.param({"prefix_cache": True, "kv_dtype": "int8"},
                 id="int8-prefix"),
    pytest.param({"prefix_cache": True, "kv_dtype": "int8",
                  "speculative": True, "draft_k": 3}, id="speculative"),
]


@pytest.mark.parametrize("kw", COMBOS)
def test_tp_streams_bit_identical(model, kw):
    ref, e1 = _run(model, **kw)
    got, e2 = _run(model, mp=2, **kw)
    assert got == ref, "mp=2 streams diverged from mp=1"
    assert len(got) == 3
    assert e1.pool.used_blocks == 0 and e2.pool.used_blocks == 0
    assert e2.stats()["mp"] == 2
    # the compiled-shape family is bounded: sharding changes the mesh a
    # program runs on, never which programs exist
    assert (sorted(e2.stats()["compiles"])
            == sorted(e1.stats()["compiles"]))


def test_tp_parity_under_eviction(model):
    # pool sized to starve at mp=2 exactly as at mp=1: eviction order is
    # host-side and rank-replicated, so the re-derived streams match
    kw = dict(speculative=True, draft_k=4, num_blocks=5)
    ref, _ = _run(model, **kw)
    got, eng = _run(model, mp=2, **kw)
    assert got == ref
    assert eng.pool.used_blocks == 0
    assert eng.preemptions >= 0  # eviction path exercised without leaks


def test_tp_mp4_streams_bit_identical(model):
    # NKV % mp must hold, so mp=4 needs a wider-kv config than the
    # module model (kv_heads=2): one kv head per rank here
    cfg = llama_tiny(vocab=96, hidden=64, layers=1, heads=4, kv_heads=4,
                     seq=512)
    m = (cfg, init_llama_params(cfg, seed=5))
    ref, _ = _run(m)
    got, eng = _run(m, mp=4)
    assert got == ref
    assert eng.pool.used_blocks == 0 and eng.stats()["mp"] == 4


# -- per-rank pool accounting -------------------------------------------------

def test_tp_pool_bytes_per_rank_halve(model):
    kw = dict(prefix_cache=True, kv_dtype="int8", speculative=True,
              draft_k=3)
    _, e1 = _run(model, **kw)
    _, e2 = _run(model, mp=2, **kw)
    s1, s2 = e1.stats(), e2.stats()
    assert s1["mp"] == 1 and s2["mp"] == 2
    # every pool (int8 kv, fp32 scales, fp16 draft) shards on the
    # kv-head axis, so one rank holds exactly half the device bytes
    assert s1["pool_bytes_per_rank"] == 2 * s2["pool_bytes_per_rank"]
    assert s2["pool_bytes_per_rank"] > 0


def test_tp_rejects_indivisible_heads(model):
    cfg, params = model  # kv_heads=2: mp=4 cannot shard the KV pools
    serve = ServeConfig(block_size=128, num_blocks=10, max_batch=2,
                        prefill_chunk=32, max_seq_len=256, mp=4)
    with pytest.raises(ValueError, match="num_key_value_heads"):
        InferenceEngine(params, cfg, serve)


def test_tp_env_knob_sets_degree(model, monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_SERVE_MP", "2")
    ref, _ = _run(model)  # ServeConfig(mp=) absent -> env knob wins
    cfg, params = model
    serve = ServeConfig(block_size=128, num_blocks=10, max_batch=2,
                        prefill_chunk=32, max_seq_len=256)
    eng = InferenceEngine(params, cfg, serve)
    assert eng.mp == 2
    monkeypatch.setenv("PADDLE_TPU_SERVE_MP", "1")


# -- sharded weight swap ------------------------------------------------------

def _copy(tree):
    import jax
    # fresh containers, same leaves: swap_fill mutates dicts in place
    return jax.tree_util.tree_map(lambda a: a, tree)


def test_tp_sharded_swap_drains_and_stays_sharded(model):
    cfg, params = model
    ref, _ = _run(model)  # mp=1, no swap: the bitwise reference

    serve = ServeConfig(block_size=128, num_blocks=10, max_batch=2,
                        prefill_chunk=32, max_seq_len=256, mp=2)
    eng = InferenceEngine(params, cfg, serve, record_events=True)
    # the swap source is an UNSHARDED host-side copy: _apply_swap must
    # re-place every leaf onto the engine's sharded layout
    eng.swap_weights(_copy(params), at_iteration=3)
    stats = eng.run(_requests(), deterministic=True)

    got = {s.req.request_id: s.generated for s in eng.finished}
    assert got == ref  # identical swap is bit-identical, zero drops
    assert stats["weight_swaps"] == 1 and stats["unfinished"] == 0
    assert (eng.last_swap["in_flight_running"]
            + eng.last_swap["in_flight_prefill"]) >= 1
    assert eng.pool.used_blocks == 0
    # the swapped-in weights landed on the mp mesh, not replicated
    assert not eng.params["lm_head"].sharding.is_fully_replicated
    assert not eng.params["embed"].sharding.is_fully_replicated


# -- crash matrix, sharded ----------------------------------------------------

MATRIX = [
    ("serve.admit.before", 2),
    ("serve.admit.after", 2),
    ("serve.prefill.before", 2),
    ("serve.prefill.after", 2),
    ("serve.decode.before", 3),
    ("serve.decode.after", 3),
    ("serve.swap.before", 1),
    ("serve.swap.after", 1),
]

_TP_KW = dict(mp=2, prefix_cache=True, kv_dtype="int8", speculative=True,
              draft_k=3)


def _shared_requests(n=3, max_new=6, seed=7):
    """Identical 150-token prompts: one full shared block, so the
    prefix cache registers + hits on the sharded pools."""
    rng = np.random.RandomState(seed)
    prompt = rng.randint(1, 96, size=150).tolist()
    return [Request(list(prompt), max_new_tokens=max_new,
                    arrival=float(i), request_id=i) for i in range(n)]


@pytest.fixture(scope="module")
def tp_crash_ref(model, tmp_path_factory):
    """Unkilled sharded reference streams (computed once for the
    matrix), with the same mid-run weight swap the matrix runs
    schedule."""
    tmp = tmp_path_factory.mktemp("tpref")
    cfg, params = model
    with _common.interpret_mode(True):
        eng = InferenceEngine(
            params, cfg,
            ServeConfig(block_size=128, num_blocks=10, max_batch=2,
                        prefill_chunk=32, max_seq_len=256, **_TP_KW),
            journal=str(tmp / "ref19.jsonl"))
        eng.swap_weights(_copy(params), at_iteration=4)
        eng.run(_shared_requests(), deterministic=True)
        ref = {s.req.request_id: s.generated for s in eng.finished}
    assert len(ref) == 3
    assert eng.pool.used_blocks == 0
    # identical prompts -> identical greedy streams, via cache hits
    assert len({tuple(t) for t in ref.values()}) == 1
    return ref


@pytest.mark.parametrize("point,nth", MATRIX,
                         ids=[f"{p}-tp" for p, _ in MATRIX])
def test_crash_matrix_recovers_bit_identical_sharded(model, tmp_path,
                                                     tp_crash_ref, point,
                                                     nth):
    """The full PR-14 fault matrix on a SHARDED engine with speculation,
    prefix caching and int8 KV on. The journal stays host-side and
    rank-replicated, recovery replays into a fresh sharded engine, and
    every re-derived stream is bitwise the unkilled sharded stream —
    which is itself bitwise the mp=1 stream."""
    cfg, params = model
    path = str(tmp_path / "kill19.jsonl")
    reqs = _shared_requests()
    serve_kw = dict(block_size=128, num_blocks=10, max_batch=2,
                    prefill_chunk=32, max_seq_len=256, **_TP_KW)

    eng = InferenceEngine(params, cfg, ServeConfig(**serve_kw),
                          journal=path)
    eng.swap_weights(_copy(params), at_iteration=4)
    with faults.scope(point, "raise", nth=nth) as plan:
        with pytest.raises(faults.FaultError):
            eng.run(reqs, deterministic=True)
        assert plan.fired == 1
        # the crash path released every live block on the sharded pool
        assert eng.pool.used_blocks == 0

        # recover into a FRESH sharded engine over the same journal
        eng2 = InferenceEngine(params, cfg, ServeConfig(**serve_kw),
                               journal=path)
        rec = eng2.recover()
        assert rec["torn_lines"] == 0
        journaled = ({s.req.request_id for s in eng2.waiting}
                     | {s.req.request_id for s in eng2.finished})
        resubmit = [Request(r.prompt, max_new_tokens=r.max_new_tokens,
                            request_id=r.request_id)
                    for r in reqs if r.request_id not in journaled]
        eng2.run(resubmit, deterministic=True)

    got = {s.req.request_id: s.generated for s in eng2.finished}
    assert got == tp_crash_ref, f"sharded streams diverged at {point}"
    assert eng2.pool.used_blocks == 0
    st = read_journal(path)
    assert st.finished == set(tp_crash_ref)
    assert st.torn_lines == 0


if __name__ == "__main__":
    pytest.main([__file__, "-q"])
