"""paddle.sparse tests: COO/CSR creation+conversion, ops vs dense reference,
autograd through sparse values."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import sparse


def _rand_coo(shape, density=0.3, seed=0):
    rng = np.random.RandomState(seed)
    dense = rng.randn(*shape).astype(np.float32)
    dense[rng.rand(*shape) > density] = 0.0
    return dense


class TestCreation:
    def test_coo_roundtrip(self):
        dense = _rand_coo((5, 6))
        t = paddle.Tensor(dense).to_sparse_coo()
        assert t.is_sparse() and t.is_sparse_coo()
        assert t.nnz() == int((dense != 0).sum())
        np.testing.assert_allclose(t.to_dense().numpy(), dense)

    def test_csr_roundtrip(self):
        dense = _rand_coo((4, 7), seed=1)
        t = paddle.Tensor(dense).to_sparse_csr()
        assert t.is_sparse_csr()
        np.testing.assert_allclose(t.to_dense().numpy(), dense)
        back = t.to_sparse_coo()
        np.testing.assert_allclose(back.to_dense().numpy(), dense)

    def test_sparse_coo_tensor_ctor(self):
        idx = np.array([[0, 1, 2], [1, 2, 0]])
        vals = np.array([1.0, 2.0, 3.0], np.float32)
        t = sparse.sparse_coo_tensor(idx, vals, [3, 3])
        want = np.zeros((3, 3), np.float32)
        want[0, 1], want[1, 2], want[2, 0] = 1, 2, 3
        np.testing.assert_allclose(t.to_dense().numpy(), want)

    def test_coalesce_sums_duplicates(self):
        idx = np.array([[0, 0], [1, 1]])
        t = sparse.sparse_coo_tensor(idx, np.array([2.0, 5.0], np.float32), [2, 2])
        c = t.coalesce()
        assert c.nnz() == 1
        assert float(c.values()) == 7.0

    def test_csr_fields(self):
        dense = np.array([[1, 0, 2], [0, 0, 3]], np.float32)
        t = paddle.Tensor(dense).to_sparse_csr()
        np.testing.assert_array_equal(t.crows().numpy(), [0, 2, 3])
        np.testing.assert_array_equal(t.cols().numpy(), [0, 2, 2])
        np.testing.assert_allclose(t.values().numpy(), [1, 2, 3])


class TestOps:
    def test_elementwise(self):
        a, b = _rand_coo((6, 5), seed=2), _rand_coo((6, 5), seed=3)
        sa = paddle.Tensor(a).to_sparse_coo()
        sb = paddle.Tensor(b).to_sparse_coo()
        np.testing.assert_allclose((sa + sb).to_dense().numpy(), a + b, rtol=1e-5)
        np.testing.assert_allclose((sa - sb).to_dense().numpy(), a - b, rtol=1e-5)
        np.testing.assert_allclose(sparse.multiply(sa, sb).to_dense().numpy(),
                                   a * b, rtol=1e-5)

    def test_matmul_coo_csr(self):
        a = _rand_coo((5, 8), seed=4)
        y = np.random.RandomState(5).randn(8, 3).astype(np.float32)
        for conv in ("to_sparse_coo", "to_sparse_csr"):
            sa = getattr(paddle.Tensor(a), conv)()
            out = sparse.matmul(sa, paddle.Tensor(y))
            np.testing.assert_allclose(out.numpy(), a @ y, rtol=1e-4, atol=1e-5)

    def test_masked_matmul(self):
        rng = np.random.RandomState(6)
        x = rng.randn(4, 6).astype(np.float32)
        y = rng.randn(6, 5).astype(np.float32)
        mask = paddle.Tensor(_rand_coo((4, 5), seed=7)).to_sparse_coo()
        out = sparse.masked_matmul(paddle.Tensor(x), paddle.Tensor(y), mask)
        full = x @ y
        want = np.where(mask.to_dense().numpy() != 0, full, 0)
        np.testing.assert_allclose(out.to_dense().numpy(), want, rtol=1e-4, atol=1e-5)

    def test_transpose_unary(self):
        a = _rand_coo((3, 4), seed=8)
        sa = paddle.Tensor(a).to_sparse_coo()
        np.testing.assert_allclose(sparse.transpose(sa, [1, 0]).to_dense().numpy(),
                                   a.T)
        np.testing.assert_allclose(sparse.sin(sa).to_dense().numpy(), np.sin(a),
                                   rtol=1e-5, atol=1e-6)
        assert abs(float(sparse.sum(sa)) - a.sum()) < 1e-4

    def test_softmax(self):
        a = _rand_coo((4, 6), seed=9)
        sa = paddle.Tensor(a).to_sparse_csr()
        sm = sparse.nn.functional.softmax(sa)
        dense = sm.to_dense().numpy()
        mask = a != 0
        for r in range(4):
            if mask[r].any():
                vals = a[r][mask[r]]
                want = np.exp(vals - vals.max())
                want = want / want.sum()
                np.testing.assert_allclose(dense[r][mask[r]], want, rtol=1e-4)


class TestAutogradAndNN:
    def test_grad_through_values(self):
        dense = _rand_coo((5, 4), seed=10)
        t = paddle.Tensor(dense).to_sparse_coo()
        t.stop_gradient = False
        y = np.random.RandomState(11).randn(4, 2).astype(np.float32)
        out = sparse.matmul(t, paddle.Tensor(y))
        out.sum().backward()
        g = t.grad
        assert g is not None and g.shape == [t.nnz()]
        # d/dv sum(v_k * y[col_k, :]) = y[col_k, :].sum()
        idx = t.indices().numpy()
        want = y[idx[1]].sum(-1)
        np.testing.assert_allclose(g.numpy(), want, rtol=1e-5)

    def test_relu_layer_and_bn(self):
        a = _rand_coo((6, 8), seed=12)
        sa = paddle.Tensor(a).to_sparse_coo()
        out = sparse.nn.ReLU()(sa)
        np.testing.assert_allclose(out.to_dense().numpy(), np.maximum(a, 0))

        bn = sparse.nn.BatchNorm(3)
        vals_in = paddle.Tensor(np.random.RandomState(13).randn(10, 3).astype(np.float32))
        coo = sparse.sparse_coo_tensor(
            np.stack([np.arange(10), np.arange(10)]), vals_in, [10, 10, 3])
        out = bn(coo)
        v = out.values().numpy()
        np.testing.assert_allclose(v.mean(0), bn.bias.numpy(), atol=1e-4)


class TestRegressions:
    def test_transpose_dense_dims(self):
        dense = np.arange(2 * 2 * 3 * 4, dtype=np.float32).reshape(2, 2, 3, 4)
        coo = sparse.sparse_coo_tensor(np.array([[0, 1], [1, 0]]),
                                       dense[[0, 1], [1, 0]], [2, 2, 3, 4])
        tr = sparse.transpose(coo, [0, 1, 3, 2])
        np.testing.assert_allclose(tr.to_dense().numpy(),
                                   coo.to_dense().numpy().transpose(0, 1, 3, 2))
        with pytest.raises(ValueError):
            sparse.transpose(coo, [2, 1, 0, 3])

    def test_empty_coo_inferred_shape(self):
        e = sparse.sparse_coo_tensor(np.zeros((2, 0), np.int64),
                                     np.zeros((0,), np.float32))
        assert e.shape == [0, 0] and e.nnz() == 0

    def test_coalesce_idempotent(self):
        coo = sparse.sparse_coo_tensor(np.array([[0], [1]]),
                                       np.ones(1, np.float32), [2, 2])
        c1 = coo.coalesce()
        assert c1.coalesce() is c1


class TestSparseGradEdges:
    """Sparse gradient coverage beyond the basic matmul case (VERDICT r2
    missing #6: 'sparse grad cases'): grads through mv, elementwise and
    unary sparse ops, checked against finite differences of the dense
    equivalent (masked_matmul forward coverage lives in TestSparseOps)."""

    def _fd(self, f_np, vals, eps=1e-3):
        g = np.zeros_like(vals)
        for i in range(vals.size):
            vp = vals.copy(); vp[i] += eps
            vm = vals.copy(); vm[i] -= eps
            g[i] = (f_np(vp) - f_np(vm)) / (2 * eps)
        return g

    def test_mv_grad(self):
        dense = _rand_coo((4, 3), seed=20)
        t = paddle.Tensor(dense).to_sparse_coo()
        t.stop_gradient = False
        vec = np.random.RandomState(21).randn(3).astype(np.float32)
        out = sparse.mv(t, paddle.Tensor(vec))
        (out ** 2).sum().backward()
        idx = t.indices().numpy()
        vals = t.values().numpy()

        def f_np(v):
            d = np.zeros((4, 3), np.float32)
            d[idx[0], idx[1]] = v
            return ((d @ vec) ** 2).sum()
        np.testing.assert_allclose(t.grad.numpy(), self._fd(f_np, vals),
                                   rtol=2e-2, atol=2e-3)

    def test_unary_grad_chain(self):
        dense = np.abs(_rand_coo((5, 5), seed=22)) + 0.5  # positive values
        t = paddle.Tensor(dense).to_sparse_coo()
        t.stop_gradient = False
        out = sparse.sqrt(t)
        out.values().sum().backward()
        vals = t.values().numpy()
        np.testing.assert_allclose(t.grad.numpy(), 0.5 / np.sqrt(vals),
                                   rtol=1e-4)

    def test_elementwise_grad_both_sides(self):
        a_d = _rand_coo((4, 4), seed=23)
        # same sparsity pattern for both operands
        b_vals_rng = np.random.RandomState(24)
        a = paddle.Tensor(a_d).to_sparse_coo()
        a.stop_gradient = False
        b_vals = b_vals_rng.randn(a.nnz()).astype(np.float32)
        b = sparse.sparse_coo_tensor(a.indices(), b_vals, a.shape)
        b.stop_gradient = False
        out = sparse.multiply(a, b)
        out.values().sum().backward()
        np.testing.assert_allclose(a.grad.numpy(), b_vals, rtol=1e-5)
        np.testing.assert_allclose(b.grad.numpy(), a.values().numpy(),
                                   rtol=1e-5)
