"""Speculative decoding in the serving engine (PR 18).

The contract under test is PARITY.md's: every token a speculative
engine emits is the BASE model's own greedy argmax at its position —
the draft model only decides how many positions one iteration can
confirm — so streams are token-bitwise-identical to sequential decode
with speculation off, for any draft (including a garbage one), with
int8 KV on, through prefix-cache sharing and through eviction. The
compiled-shape family stays bounded: one draft-prefill program, one
draft-decode and one verify program per decode bucket (token width
pinned at K+1).
"""
import numpy as np
import pytest

from paddle_tpu.inference import InferenceEngine, Request, ServeConfig
from paddle_tpu.models.llama import (init_llama_params, llama_tiny,
                                     make_draft_model)
from paddle_tpu.ops import _common


@pytest.fixture(autouse=True)
def _interpret():
    with _common.interpret_mode(True):
        yield


@pytest.fixture(scope="module")
def model():
    # two layers so the default draft (first layer only) genuinely
    # disagrees with the base model and rejection paths are exercised
    cfg = llama_tiny(vocab=96, hidden=64, layers=2, heads=4, kv_heads=2,
                     seq=512)
    return cfg, init_llama_params(cfg, seed=0)


def _requests(max_new=8):
    rng = np.random.RandomState(7)
    # one multi-block prompt (130 > block_size) to cross block
    # boundaries inside the verify window
    return [Request(rng.randint(1, 90, size=n).tolist(),
                    max_new_tokens=max_new, arrival=float(i),
                    request_id=i)
            for i, n in enumerate([9, 40, 130])]


def _run(model, **kw):
    cfg, params = model
    eng_kw = {k: kw.pop(k) for k in ("draft_params", "draft_config")
              if k in kw}
    serve = ServeConfig(block_size=128, num_blocks=kw.pop("num_blocks", 10),
                        max_batch=2, prefill_chunk=32, max_seq_len=256,
                        **kw)
    eng = InferenceEngine(params, cfg, serve, **eng_kw)
    eng.run(_requests(), deterministic=True)
    return {s.req.request_id: s.generated for s in eng.finished}, eng


@pytest.fixture(scope="module")
def reference(model):
    streams, _ = _run(model, speculative=False)
    assert len(streams) == 3
    return streams


@pytest.fixture(scope="module")
def spec_run(model):
    return _run(model, speculative=True, draft_k=3)


def test_spec_streams_bit_identical(spec_run, reference):
    streams, eng = spec_run
    assert streams == reference
    sp = eng.stats()["speculative"]
    assert sp["draft_k"] == 3 and sp["draft_layers"] == 1
    assert sp["proposed"] > 0
    assert 0.0 <= sp["accept_rate"] <= 1.0


def test_spec_parity_int8_and_prefix_cache(model):
    ref, _ = _run(model, speculative=False, kv_dtype="int8")
    got, eng = _run(model, speculative=True, draft_k=3, kv_dtype="int8",
                    prefix_cache=True)
    assert got == ref
    assert eng.pool.used_blocks == 0


def test_spec_parity_under_eviction(model, reference):
    # pool sized to starve: lookahead shrinks, then eviction fires;
    # dropped draft tokens must cost only latency, never tokens
    got, eng = _run(model, speculative=True, draft_k=4, num_blocks=5)
    assert got == reference
    assert eng.pool.used_blocks == 0


def test_garbage_draft_never_affects_outputs(model, reference):
    # a draft with unrelated random weights proposes mostly-rejected
    # tokens; outputs must be the base model's stream regardless
    cfg, params = model
    _, dcfg = make_draft_model(params, cfg)
    dparams = init_llama_params(dcfg, seed=99)
    got, eng = _run(model, speculative=True, draft_k=2,
                    draft_params=dparams, draft_config=dcfg)
    assert got == reference
    sp = eng.stats()["speculative"]
    assert sp["accept_rate"] < 1.0


def test_spec_bounded_compiles_and_metrics(spec_run):
    _, eng = spec_run
    compiles = set(eng.stats()["compiles"])
    # draft and verify programs are each counted per decode bucket;
    # no plain-decode program ever compiles with speculation on
    assert compiles <= {"prefill_32", "draft_prefill_32",
                        "draft_1", "draft_2", "verify_1", "verify_2"}
    assert any(k.startswith("verify_") for k in compiles)
    assert any(k.startswith("draft_") for k in compiles)
    snap = eng.registry.snapshot()
    assert "spec_accept_rate" in snap
    rendered = eng.registry.render_prometheus()
    assert "paddle_tpu_serve_spec_accept_rate" in rendered


def test_commit_schedule_pure():
    # host-visible oracle for the commit schedule: layer-major order,
    # rejected columns redirected to the null block, first-visit flags
    # exactly at (layer, block) transitions
    import jax.numpy as jnp
    from paddle_tpu.ops.paged_attention import (N_COMMIT_FIELDS, _CB,
                                                _CCOL, _CFIRST, _CL,
                                                _CSEQ, _CT,
                                                paged_commit_schedule)
    tables = jnp.asarray([[2, 3, 0, 0], [5, 0, 0, 0]], jnp.int32)
    qstart = jnp.asarray([126, 4], jnp.int32)
    clen = jnp.asarray([3, 0], jnp.int32)
    sc = np.asarray(paged_commit_schedule(qstart, clen, tables,
                                          n_layers=2, n_tokens=4,
                                          block_size=128))
    assert sc.shape == (N_COMMIT_FIELDS, 2 * 2 * 4)
    # seq 0 commits positions 126,127 (block 2) and 128 (block 3);
    # its 4th slot and all of seq 1 scribble the null block
    j0 = [j for j in range(sc.shape[1])
          if sc[_CL, j] == 0 and sc[_CSEQ, j] == 0]
    assert [int(sc[_CB, j]) for j in j0] == [2, 2, 3, 0]
    assert [int(sc[_CCOL, j]) for j in j0] == [126, 127, 0, 1]
    assert [int(sc[_CT, j]) for j in j0] == [0, 1, 2, 3]
    j1 = [j for j in range(sc.shape[1])
          if sc[_CL, j] == 0 and sc[_CSEQ, j] == 1]
    assert all(int(sc[_CB, j]) == 0 for j in j1)
    # first flags: one per (layer, block) run over consecutive columns
    runs = []
    for j in range(sc.shape[1]):
        key = (int(sc[_CL, j]), int(sc[_CB, j]))
        if sc[_CFIRST, j]:
            runs.append(key)
        else:
            assert runs and runs[-1] == key
    assert all(a != b for a, b in zip(runs, runs[1:]))
    assert len(runs) >= 4


if __name__ == "__main__":
    pytest.main([__file__, "-q"])
