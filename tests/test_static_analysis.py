"""Tier-1 gate: ``python -m paddle_tpu.analysis --strict`` must stay
clean on the repo. Each registered rule is a separate parametrized case
so a regression names the rule that caught it (all cases share ONE repo
scan), and the CLI case drives the real argparse entry point in-process
— the same code path the multichip-dryrun preamble and the console run."""
import json
import os

import pytest

from paddle_tpu import envs
from paddle_tpu.analysis import REPO_ROOT, all_rules, run
from paddle_tpu.analysis.__main__ import main as cli_main


@pytest.fixture(scope="module")
def repo_report():
    # one full default scan (all rules, floors on) shared by every case
    return run()


@pytest.mark.parametrize("code", sorted(all_rules()) + ["PTA000"])
def test_repo_is_clean_per_rule(repo_report, code):
    bad = [f for f in repo_report.active if f.rule == code]
    assert not bad, "\n".join(f.format() for f in bad)


def test_no_active_findings_at_all(repo_report):
    assert not repo_report.active, \
        "\n".join(f.format() for f in repo_report.active)


def test_every_suppression_and_grant_carries_a_reason(repo_report):
    for f in repo_report.suppressed + repo_report.allowlisted:
        assert f.reason, f"{f.format()} suppressed without a reason"


@pytest.mark.slow   # same full-repo strict pass as the baseline-check gate below, which stays tier-1; keeping one CLI sweep per run as the repo grows
def test_cli_strict_exits_zero_and_emits_json(capsys):
    rc = cli_main(["--strict", "--json"])
    out = capsys.readouterr().out
    assert rc == 0, out
    rec = json.loads(out)
    assert rec["total_active"] == 0
    assert set(rec["rules"]) >= set(all_rules())


def test_cli_strict_baseline_check_is_the_ci_gate(capsys):
    """The exact invocation CI and the multichip-dryrun preamble run:
    new findings AND stale baseline entries both fail it."""
    rc = cli_main(["--strict", "--baseline", "check"])
    out = capsys.readouterr().out
    assert rc == 0, out


def test_checked_in_baseline_has_no_stale_entries(repo_report):
    from paddle_tpu.analysis import apply_baseline
    stale = apply_baseline(repo_report)
    assert not stale, f"stale baseline entries: {stale}"
    # the ratchet only ever shrinks: the checked-in baseline is empty
    # today, so every new finding fails CI immediately
    from paddle_tpu.analysis import DEFAULT_BASELINE, load_baseline
    assert os.path.exists(DEFAULT_BASELINE)
    assert load_baseline() == {}


def test_cli_strict_fails_on_a_dirty_fixture(capsys):
    fixture = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "analysis_fixtures", "pta001_bad.py")
    rc = cli_main(["--strict", "--rule", "PTA001", "--no-scope",
                   "--no-floors", fixture])
    assert rc == 1
    assert "PTA001" in capsys.readouterr().out


def test_readme_documents_every_registered_knob():
    with open(os.path.join(REPO_ROOT, "README.md"), encoding="utf-8") as fh:
        readme = fh.read()
    missing = [k.name for k in envs.knobs() if k.name not in readme]
    assert not missing, f"knobs missing from README.md: {missing}"


if __name__ == "__main__":
    pytest.main([__file__, "-q"])
