"""Static Program capture/replay, jit.save/load (StableHLO), inference
Predictor (static/, jit/save_load.py, inference/)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import inference, nn, static
from paddle_tpu.static import Executor, Program, program_guard


@pytest.fixture(autouse=True)
def _leave_eager():
    yield
    paddle.disable_static()


def test_program_capture_and_replay():
    paddle.enable_static()
    main = Program()
    with program_guard(main):
        x = static.data("x", [None, 4], "float32")
        w = paddle.to_tensor(np.ones((4, 3), np.float32))
        y = paddle.matmul(x, w) + 1.0
    paddle.disable_static()
    assert len(main.ops) >= 2

    exe = Executor()
    feed_x = np.arange(8, dtype=np.float32).reshape(2, 4)
    out = exe.run(main, feed={"x": feed_x}, fetch_list=[y])[0]
    np.testing.assert_allclose(out, feed_x @ np.ones((4, 3)) + 1.0, rtol=1e-6)

    # Different batch size (dynamic leading dim) recompiles and works.
    feed_x2 = np.ones((5, 4), np.float32)
    out2 = exe.run(main, feed={"x": feed_x2}, fetch_list=[y])[0]
    assert out2.shape == (5, 3)


def test_program_replay_with_layer_and_updated_params():
    paddle.enable_static()
    main = Program()
    with program_guard(main):
        x = static.data("x", [None, 8], "float32")
        fc = nn.Linear(8, 2)
        y = fc(x)
    paddle.disable_static()

    exe = Executor()
    feed = np.random.RandomState(0).randn(3, 8).astype(np.float32)
    out1 = exe.run(main, feed={"x": feed}, fetch_list=[y])[0]
    # Mutate the weights; replay must see the new values (params are inputs,
    # not baked constants).
    fc.weight.set_value(np.zeros_like(fc.weight.numpy()))
    fc.bias.set_value(np.full_like(fc.bias.numpy(), 5.0))
    out2 = exe.run(main, feed={"x": feed}, fetch_list=[y])[0]
    np.testing.assert_allclose(out2, np.full((3, 2), 5.0), rtol=1e-6)
    assert not np.allclose(out1, out2)


def test_static_grads_via_fetch():
    paddle.enable_static()
    main = Program()
    with program_guard(main):
        x = static.data("x", [2, 3], "float32")
        w = paddle.to_tensor(np.full((3, 1), 2.0, np.float32))
        w.stop_gradient = False
        loss = paddle.mean(paddle.matmul(x, w))
    paddle.disable_static()
    exe = Executor()
    feed = np.ones((2, 3), np.float32)
    outs, grads = exe.run(main, feed={"x": feed}, fetch_list=[loss],
                          fetch_grads_of=[w])
    np.testing.assert_allclose(outs[0], 6.0, rtol=1e-6)
    # d(mean(x@w))/dw = mean over batch of x = ones/ (2*1) * 2 rows -> 1/1?
    np.testing.assert_allclose(np.asarray(grads[0]),
                               np.full((3, 1), 1.0 / 1.0 / 1.0 * 2 / 2),
                               rtol=1e-6)


def test_jit_save_load_roundtrip(tmp_path):
    model = nn.Sequential(nn.Linear(6, 16), nn.ReLU(), nn.Linear(16, 4))
    model.eval()
    x = paddle.to_tensor(np.random.RandomState(1).randn(2, 6).astype(np.float32))
    ref = model(x).numpy()

    path = str(tmp_path / "m")
    paddle.jit.save(model, path,
                    input_spec=[static.InputSpec([2, 6], "float32")])
    loaded = paddle.jit.load(path)
    got = loaded(x)[0].numpy() if isinstance(loaded(x), (list, tuple)) \
        else loaded(x).numpy()
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)
    with pytest.raises(RuntimeError):
        loaded.train()


def test_save_load_inference_model_and_predictor(tmp_path):
    paddle.enable_static()
    main = Program()
    with program_guard(main):
        x = static.data("x", [4, 5], "float32")
        fc = nn.Linear(5, 3)
        y = nn.functional.softmax(fc(x))
    paddle.disable_static()

    prefix = str(tmp_path / "infer_model")
    static.save_inference_model(prefix, [x], [y], program=main)

    feed = np.random.RandomState(2).randn(4, 5).astype(np.float32)
    exe = Executor()
    ref = exe.run(main, feed={"x": feed}, fetch_list=[y])[0]

    # handle-based predictor API
    config = inference.Config(prefix)
    pred = inference.create_predictor(config)
    assert pred.get_input_names() == ["x"]
    h = pred.get_input_handle("x")
    h.copy_from_cpu(feed)
    pred.run()
    out = pred.get_output_handle(pred.get_output_names()[0]).copy_to_cpu()
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(out.sum(axis=-1), 1.0, rtol=1e-5)


def test_eager_mode_unaffected_by_static_capture():
    main = Program()
    paddle.enable_static()
    with program_guard(main):
        x = static.data("x", [2, 2], "float32")
        y = x * 3.0
    paddle.disable_static()
    n_ops = len(main.ops)
    # ops executed eagerly after disable_static must not append to program
    a = paddle.to_tensor(np.ones((2, 2), np.float32))
    b = a + a
    assert len(main.ops) == n_ops
    np.testing.assert_allclose(b.numpy(), 2.0)


def test_onnx_export_contract(tmp_path):
    """Without the optional onnx package: StableHLO bundle + ImportError
    naming the dependency (the reference behaves the same re paddle2onnx)."""
    import pytest

    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    from paddle_tpu.static import InputSpec

    model = nn.Linear(4, 2)
    prefix = str(tmp_path / "m")
    with pytest.raises(ImportError, match="onnx"):
        paddle.onnx.export(model, prefix,
                           input_spec=[InputSpec([1, 4], "float32")])
    loaded = paddle.jit.load(prefix)
    out = loaded(paddle.ones([1, 4]))
    assert list(out.shape) == [1, 2]


def test_predictor_clone_pool_and_config_surface(tmp_path):
    """Predictor.clone / PredictorPool share the loaded model; Config
    accessors + summary (ref: paddle_infer Config/Predictor API)."""
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu import nn
    from paddle_tpu.inference import (Config, PredictorPool, create_predictor,
                                      get_num_bytes_of_data_type, get_version)

    paddle.seed(0)
    paddle.enable_static()
    main = Program()
    with program_guard(main):
        x = static.data("x", [2, 4], "float32")
        y = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))(x)
    paddle.disable_static()
    prefix = str(tmp_path / "m")
    static.save_inference_model(prefix, [x], [y], program=main)

    cfg = Config()
    cfg.set_model(prefix + ".pdmodel")
    cfg.disable_gpu()
    cfg.enable_memory_optim()
    assert "model_prefix" in cfg.summary() and "XLA" in cfg.summary()
    assert cfg.prog_file().endswith(".pdmodel")

    pred = create_predictor(cfg)
    name = pred.get_input_names()[0]
    xin = np.random.RandomState(0).randn(2, 4).astype("float32")
    pred.get_input_handle(name).copy_from_cpu(xin)
    pred.run()
    out = pred.get_output_handle(pred.get_output_names()[0]).copy_to_cpu()

    clone = pred.clone()
    assert clone._model is pred._model  # weights + executables shared
    clone.get_input_handle(name).copy_from_cpu(xin)
    clone.run()
    out2 = clone.get_output_handle(clone.get_output_names()[0]).copy_to_cpu()
    np.testing.assert_allclose(out, out2, rtol=1e-6)

    pool = PredictorPool(cfg, 3)
    outs = []
    for i in range(3):
        p = pool.retrieve(i)
        p.get_input_handle(name).copy_from_cpu(xin)
        p.run()
        outs.append(p.get_output_handle(p.get_output_names()[0]).copy_to_cpu())
    np.testing.assert_allclose(outs[0], outs[2], rtol=1e-6)

    assert get_num_bytes_of_data_type("float32") == 4
    assert isinstance(get_version(), str)
