"""Core Tensor op tests vs numpy (modeled on the reference's OpTest strategy:
forward checked against a numpy reference, grads against numeric/jax grads)."""
import numpy as np
import pytest

import paddle_tpu as paddle


def test_to_tensor_and_meta():
    x = paddle.to_tensor([[1.0, 2.0], [3.0, 4.0]])
    assert x.shape == [2, 2]
    assert x.dtype == paddle.float32
    assert x.numel() == 4
    assert x.ndim == 2
    np.testing.assert_allclose(x.numpy(), [[1, 2], [3, 4]])


def test_dtypes():
    assert paddle.to_tensor([1, 2]).dtype == paddle.int64
    assert paddle.to_tensor([1.0]).dtype == paddle.float32
    x = paddle.to_tensor([1.0], dtype="bfloat16")
    assert x.dtype == paddle.bfloat16
    y = x.astype("float32")
    assert y.dtype == paddle.float32


def test_arithmetic():
    a = paddle.to_tensor([1.0, 2.0, 3.0])
    b = paddle.to_tensor([4.0, 5.0, 6.0])
    np.testing.assert_allclose((a + b).numpy(), [5, 7, 9])
    np.testing.assert_allclose((a - b).numpy(), [-3, -3, -3])
    np.testing.assert_allclose((a * b).numpy(), [4, 10, 18])
    np.testing.assert_allclose((b / a).numpy(), [4, 2.5, 2], rtol=1e-6)
    np.testing.assert_allclose((a ** 2).numpy(), [1, 4, 9])
    np.testing.assert_allclose((2 + a).numpy(), [3, 4, 5])
    np.testing.assert_allclose((-a).numpy(), [-1, -2, -3])


def test_reductions():
    x = paddle.to_tensor(np.arange(12, dtype=np.float32).reshape(3, 4))
    assert x.sum().item() == 66
    np.testing.assert_allclose(x.mean(axis=0).numpy(), np.arange(12).reshape(3, 4).mean(0))
    np.testing.assert_allclose(x.max(axis=1).numpy(), [3, 7, 11])
    np.testing.assert_allclose(paddle.logsumexp(x, axis=1).numpy(),
                               np.log(np.exp(np.arange(12).reshape(3, 4)).sum(1)), rtol=1e-5)


def test_manipulation():
    x = paddle.arange(24).reshape([2, 3, 4])
    assert x.transpose([2, 0, 1]).shape == [4, 2, 3]
    assert paddle.concat([x, x], axis=1).shape == [2, 6, 4]
    assert paddle.stack([x, x]).shape == [2, 2, 3, 4]
    parts = paddle.split(x, 3, axis=1)
    assert len(parts) == 3 and parts[0].shape == [2, 1, 4]
    parts = paddle.split(x, [1, -1], axis=1)
    assert parts[1].shape == [2, 2, 4]
    assert paddle.squeeze(paddle.ones([1, 3, 1])).shape == [3]
    assert paddle.unsqueeze(paddle.ones([3]), [0, 2]).shape == [1, 3, 1]
    assert paddle.flatten(x, 1, 2).shape == [2, 12]
    assert paddle.tile(paddle.ones([2]), [3]).shape == [6]
    assert paddle.expand(paddle.ones([1, 3]), [5, 3]).shape == [5, 3]


def test_matmul_and_linalg():
    a = np.random.randn(3, 4).astype(np.float32)
    b = np.random.randn(4, 5).astype(np.float32)
    out = paddle.matmul(paddle.to_tensor(a), paddle.to_tensor(b))
    np.testing.assert_allclose(out.numpy(), a @ b, rtol=1e-5)
    out_t = paddle.matmul(paddle.to_tensor(a.T), paddle.to_tensor(b), transpose_x=True)
    np.testing.assert_allclose(out_t.numpy(), a @ b, rtol=1e-5)
    e = paddle.einsum("ij,jk->ik", paddle.to_tensor(a), paddle.to_tensor(b))
    np.testing.assert_allclose(e.numpy(), a @ b, rtol=1e-5)


def test_indexing_and_gather():
    x = paddle.arange(12, dtype="float32").reshape([3, 4])
    np.testing.assert_allclose(x[1].numpy(), [4, 5, 6, 7])
    np.testing.assert_allclose(x[:, 1].numpy(), [1, 5, 9])
    idx = paddle.to_tensor([2, 0])
    np.testing.assert_allclose(paddle.gather(x, idx).numpy(),
                               np.arange(12).reshape(3, 4)[[2, 0]])
    x[0, 0] = 99.0
    assert x[0, 0].item() == 99.0


def test_search_ops():
    x = paddle.to_tensor([[3.0, 1.0, 2.0], [0.0, 5.0, 4.0]])
    np.testing.assert_array_equal(paddle.argmax(x, axis=1).numpy(), [0, 1])
    vals, idx = paddle.topk(x, k=2, axis=1)
    np.testing.assert_allclose(vals.numpy(), [[3, 2], [5, 4]])
    s = paddle.sort(x, axis=1)
    np.testing.assert_allclose(s.numpy(), [[1, 2, 3], [0, 4, 5]])
    nz = paddle.nonzero(paddle.to_tensor([0, 3, 0, 5]))
    np.testing.assert_array_equal(nz.numpy().ravel(), [1, 3])


def test_logic_and_where():
    a = paddle.to_tensor([1.0, 2.0, 3.0])
    b = paddle.to_tensor([3.0, 2.0, 1.0])
    np.testing.assert_array_equal((a < b).numpy(), [True, False, False])
    w = paddle.where(a < b, a, b)
    np.testing.assert_allclose(w.numpy(), [1, 2, 1])
    assert paddle.allclose(a, a).item()


def test_random_reproducible():
    paddle.seed(7)
    a = paddle.rand([4])
    paddle.seed(7)
    b = paddle.rand([4])
    np.testing.assert_allclose(a.numpy(), b.numpy())
    assert paddle.randint(0, 10, [100]).numpy().max() < 10


def test_inplace_and_setvalue():
    x = paddle.zeros([3])
    x.set_value(np.array([1.0, 2.0, 3.0], dtype=np.float32))
    np.testing.assert_allclose(x.numpy(), [1, 2, 3])
    x.fill_(7.0)
    np.testing.assert_allclose(x.numpy(), [7, 7, 7])
