"""paddle.text: viterbi decode vs brute force; dataset offline contract."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.text import UCIHousing, ViterbiDecoder, viterbi_decode


def _brute_force(pot, trans, length, bos_eos):
    n = pot.shape[-1]
    import itertools
    tags = range(n)
    best, best_path = -np.inf, None
    for path in itertools.product(tags, repeat=length):
        s = pot[0, path[0]]
        if bos_eos:
            s += trans[n - 2, path[0]]
        for t in range(1, length):
            s += trans[path[t - 1], path[t]] + pot[t, path[t]]
        if bos_eos:
            s += trans[path[-1], n - 1]
        if s > best:
            best, best_path = s, path
    return best, list(best_path)


@pytest.mark.parametrize("bos_eos", [False, True])
def test_viterbi_matches_brute_force(bos_eos):
    rng = np.random.RandomState(0)
    T, N = 4, 4
    pot = rng.randn(1, T, N).astype(np.float32)
    trans = rng.randn(N, N).astype(np.float32)
    lens = np.array([T], np.int64)
    scores, paths = viterbi_decode(
        paddle.to_tensor(pot), paddle.to_tensor(trans),
        paddle.to_tensor(lens), include_bos_eos_tag=bos_eos)
    ref_score, ref_path = _brute_force(pot[0], trans, T, bos_eos)
    np.testing.assert_allclose(float(scores.numpy()[0]), ref_score, atol=1e-4)
    assert paths.numpy()[0].tolist() == ref_path


def test_viterbi_layer_and_batch():
    rng = np.random.RandomState(1)
    B, T, N = 3, 5, 6
    pot = paddle.to_tensor(rng.randn(B, T, N).astype(np.float32))
    trans = paddle.to_tensor(rng.randn(N, N).astype(np.float32))
    lens = paddle.to_tensor(np.array([5, 3, 4], np.int64))
    dec = ViterbiDecoder(trans, include_bos_eos_tag=False)
    scores, paths = dec(pot, lens)
    assert list(scores.shape) == [B] and list(paths.shape) == [B, T]


def test_uci_housing_local_file(tmp_path):
    rng = np.random.RandomState(2)
    rows = rng.rand(50, 14).astype(np.float32)
    f = tmp_path / "housing.data"
    np.savetxt(f, rows)
    train = UCIHousing(data_file=str(f), mode="train")
    test = UCIHousing(data_file=str(f), mode="test")
    assert len(train) == 40 and len(test) == 10
    x, y = train[0]
    assert x.shape == (13,) and y.shape == (1,)


def test_dataset_offline_error():
    with pytest.raises(RuntimeError, match="data_file"):
        UCIHousing(data_file=None)
