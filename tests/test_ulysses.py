"""Ulysses (all-to-all) context parallelism correctness (r7).

Ref: SURVEY.md §5.7 / ISSUE 7. The all-to-all heads<->sequence layout must
match full-sequence attention in fwd AND all grads at sep=2 and sep=4
(causal + non-causal, hd64/hd128), agree with the ring strategy, route GQA
on kv-head divisibility (divisible: head-sharded kv; non-divisible: ring
fallback with a warning), and validate strategy selection up front with
errors naming PADDLE_TPU_SEP_STRATEGY / sep_strategy.
"""
import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P
from jax.experimental.shard_map import shard_map

import paddle_tpu  # noqa: F401  (jax config)
import importlib

# the package re-exports the FUNCTION under the module's name; go through
# importlib for the module object (spy target)
ua = importlib.import_module("paddle_tpu.parallel.ulysses_attention")
from paddle_tpu.parallel.ring_attention import ring_attention
from paddle_tpu.parallel.ulysses_attention import (
    ENV_SEP_STRATEGY, resolve_sep_strategy, sep_strategy_default,
    ulysses_attention)


def _mesh(n):
    devs = jax.devices("cpu")[:n]
    return Mesh(np.array(devs), ("sep",))


def _sep_fn(fn, mesh):
    return shard_map(fn, mesh=mesh,
                     in_specs=(P(None, "sep"), P(None, "sep"), P(None, "sep")),
                     out_specs=P(None, "sep"), check_rep=False)


def _ulysses_fn(mesh, causal):
    return _sep_fn(functools.partial(ulysses_attention, axis_name="sep",
                                     causal=causal), mesh)


def _reference(q, k, v, causal):
    qf, kf, vf = (x.astype(jnp.float32) for x in (q, k, v))
    H, Hkv = q.shape[2], k.shape[2]
    if Hkv != H:
        kf = jnp.repeat(kf, H // Hkv, axis=2)
        vf = jnp.repeat(vf, H // Hkv, axis=2)
    s = jnp.einsum("bqhd,bkhd->bhqk", qf, kf) / np.sqrt(q.shape[-1])
    if causal:
        S = q.shape[1]
        mask = np.tril(np.ones((S, S), bool))
        s = jnp.where(mask[None, None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, vf).astype(q.dtype)


def _qkvw(B, S, H, D, seed, Hkv=None):
    rng = np.random.RandomState(seed)
    q = jnp.asarray(rng.randn(B, S, H, D), jnp.float32)
    k = jnp.asarray(rng.randn(B, S, Hkv or H, D), jnp.float32)
    v = jnp.asarray(rng.randn(B, S, Hkv or H, D), jnp.float32)
    w = jnp.asarray(rng.randn(B, S, H, D), jnp.float32)
    return q, k, v, w


@pytest.mark.parametrize("causal,sep,d", [(True, 2, 64), (True, 4, 128),
                                          (False, 4, 64)])
def test_ulysses_matches_full(causal, sep, d):
    q, k, v, _ = _qkvw(1, sep * 128, 4, d, 0)
    out = _ulysses_fn(_mesh(sep), causal)(q, k, v)
    ref = _reference(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [
    True, pytest.param(False, marks=pytest.mark.slow)])
@pytest.mark.parametrize("sep", [2, 4])
def test_ulysses_grads_match(causal, sep):
    """All grads vs single-device attention through the custom_vjp (the
    backward's do scatter + dq/dk/dv gathers), non-uniform cotangent."""
    q, k, v, w = _qkvw(1, 4 * 128, 4, 64, 1)
    uly = _ulysses_fn(_mesh(sep), causal)

    def loss_uly(q, k, v):
        return jnp.sum(uly(q, k, v).astype(jnp.float32) * w)

    def loss_ref(q, k, v):
        return jnp.sum(_reference(q, k, v, causal).astype(jnp.float32) * w)

    g_uly = jax.grad(loss_uly, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g_uly, g_ref, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4,
                                   atol=2e-4, err_msg=f"d{name}")


@pytest.mark.slow   # ulysses-vs-ring agreement is also pinned end-to-end by test_llama_sep_ulysses_path
def test_ulysses_matches_ring():
    """The two sep strategies are different dataflows over the same math —
    outputs and grads must agree within flash tolerance."""
    causal, sep = True, 4
    q, k, v, w = _qkvw(1, sep * 128, 4, 64, 2)
    mesh = _mesh(sep)
    uly = _ulysses_fn(mesh, causal)
    ring = _sep_fn(functools.partial(ring_attention, axis_name="sep",
                                    causal=causal, impl="flash"), mesh)
    np.testing.assert_allclose(np.asarray(uly(q, k, v)),
                               np.asarray(ring(q, k, v)),
                               rtol=2e-5, atol=2e-5)
    gu = jax.grad(lambda q, k, v: jnp.sum(uly(q, k, v) * w),
                  argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(lambda q, k, v: jnp.sum(ring(q, k, v) * w),
                  argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gu, gr, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4,
                                   atol=2e-4, err_msg=f"d{name}")


def test_ulysses_gqa_divisible():
    """num_kv_heads % sep == 0: kv heads ride the all-to-all un-repeated
    (the repeat happens post-scatter; its transpose sums dk/dv pre-gather)."""
    sep = 2
    q, k, v, w = _qkvw(1, sep * 128, 4, 64, 3, Hkv=2)
    uly = _ulysses_fn(_mesh(sep), True)
    out = uly(q, k, v)
    ref = _reference(q, k, v, True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)
    gk = jax.grad(lambda k: jnp.sum(uly(q, k, v) * w))(k)
    gk_ref = jax.grad(lambda k: jnp.sum(_reference(q, k, v, True) * w))(k)
    np.testing.assert_allclose(np.asarray(gk), np.asarray(gk_ref),
                               rtol=2e-4, atol=2e-4)


def test_ulysses_gqa_indivisible_falls_back_to_ring():
    """num_kv_heads=2, sep=4: no kv head split exists — warn and run the
    ring for this call, still exact."""
    sep = 4
    q, k, v, _ = _qkvw(1, sep * 128, 4, 64, 4, Hkv=2)
    with pytest.warns(RuntimeWarning, match="falling back to ring"):
        out = _ulysses_fn(_mesh(sep), True)(q, k, v)
    ref = _reference(q, k, v, True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ulysses_unaligned_shards_fall_back():
    # gathered length 4*32=128-unaligned per-shard lengths are fine as long
    # as n*S_local % 128 == 0; S_local=24 (gathered 96) is not -> xla sdpa
    sep = 4
    q, k, v, _ = _qkvw(2, sep * 24, 4, 16, 5)
    out = _ulysses_fn(_mesh(sep), True)(q, k, v)
    ref = _reference(q, k, v, True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ulysses_heads_not_divisible_raises():
    sep = 4
    q, k, v, _ = _qkvw(1, sep * 128, 2, 64, 6)  # 2 heads, sep=4
    with pytest.raises(ValueError, match="num_heads % sep == 0"):
        _ulysses_fn(_mesh(sep), True)(q, k, v)


# --- strategy selection ----------------------------------------------------

def test_env_sep_strategy_validated(monkeypatch):
    monkeypatch.setenv(ENV_SEP_STRATEGY, "ulises")
    with pytest.raises(ValueError, match=ENV_SEP_STRATEGY):
        sep_strategy_default()
    monkeypatch.setenv(ENV_SEP_STRATEGY, "ULYSSES")  # case-insensitive
    assert sep_strategy_default() == "ulysses"
    monkeypatch.delenv(ENV_SEP_STRATEGY)
    assert sep_strategy_default() == "ring"


def test_resolve_sep_strategy(monkeypatch):
    assert resolve_sep_strategy("ring") == "ring"
    assert resolve_sep_strategy("ulysses") == "ulysses"
    with pytest.raises(ValueError, match="sep_strategy"):
        resolve_sep_strategy("rings")
    monkeypatch.setenv(ENV_SEP_STRATEGY, "ulysses")
    assert resolve_sep_strategy(None) == "ulysses"


def test_build_train_step_validates_sep_strategy():
    from paddle_tpu.models.llama import (ParallelConfig, build_train_step,
                                         llama_tiny)
    cfg = llama_tiny(vocab=64, hidden=32, layers=1, heads=2, kv_heads=2,
                     inter=64, seq=256)
    with pytest.raises(ValueError, match="sep_strategy"):
        build_train_step(cfg, ParallelConfig(dp=2, sep=4,
                                             sep_strategy="alltoall"))
    # heads=2 can't head-split 4 ways: fail BEFORE tracing, naming the fix
    with pytest.raises(ValueError, match="num_heads % sep == 0"):
        build_train_step(cfg, ParallelConfig(dp=2, sep=4,
                                             sep_strategy="ulysses"))


# --- llama end-to-end ------------------------------------------------------

def test_llama_sep_ulysses_path(monkeypatch):
    """sep_strategy='ulysses' end-to-end through the llama sep shard_map
    island (sep=4, flash path): matches serial loss AND the ring strategy,
    and the env-selected route (sep_strategy=None +
    PADDLE_TPU_SEP_STRATEGY=ulysses) actually reaches the ulysses call
    (spy) with the same loss."""
    from paddle_tpu.models.llama import (ParallelConfig, build_train_step,
                                         llama_tiny)
    cfg = llama_tiny(vocab=64, hidden=64, layers=2, heads=4, kv_heads=4,
                     inter=64, seq=512)
    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg.vocab_size, (2, 512)).astype(np.int32)
    labels = np.roll(ids, -1, axis=1).astype(np.int32)

    step, p, o = build_train_step(cfg, ParallelConfig(use_flash=False,
                                                      remat=False), lr=1e-3)
    _, _, l_ref = step(p, o, ids, labels)

    par = ParallelConfig(dp=2, sep=4, use_flash=True, remat=False,
                         sep_strategy="ulysses")
    step2, p2, o2 = build_train_step(cfg, par, lr=1e-3)
    _, _, l_uly = step2(p2, o2, ids, labels)
    np.testing.assert_allclose(float(l_uly), float(l_ref), rtol=2e-4)

    ring_par = ParallelConfig(dp=2, sep=4, use_flash=True, remat=False,
                              sep_strategy="ring")
    step3, p3, o3 = build_train_step(cfg, ring_par, lr=1e-3)
    _, _, l_ring = step3(p3, o3, ids, labels)
    np.testing.assert_allclose(float(l_uly), float(l_ring), rtol=2e-4)

    # env-selected route: same config with sep_strategy=None follows
    # PADDLE_TPU_SEP_STRATEGY and must route through ulysses_attention
    monkeypatch.setenv(ENV_SEP_STRATEGY, "ulysses")
    calls = {"n": 0}
    orig = ua.ulysses_attention

    def spy(*a, **kw):
        calls["n"] += 1
        return orig(*a, **kw)

    monkeypatch.setattr(ua, "ulysses_attention", spy)
    step4, p4, o4 = build_train_step(
        cfg, ParallelConfig(dp=2, sep=4, use_flash=True, remat=False),
        lr=1e-3)
    _, _, l_env = step4(p4, o4, ids, labels)
    assert calls["n"] > 0  # ulysses actually routed
    np.testing.assert_allclose(float(l_env), float(l_uly), rtol=1e-6)
