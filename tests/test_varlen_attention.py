"""flash_attn_unpadded: packed varlen attention vs per-sequence dense
attention (ref: test/legacy_test/test_flash_attention.py unpadded cases)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F

H, HKV, D = 4, 4, 16


def _packed(lens, heads, rng):
    total = sum(lens)
    x = rng.randn(total, heads, D).astype(np.float32)
    cu = np.concatenate([[0], np.cumsum(lens)]).astype(np.int32)
    return x, cu


def _dense_ref(q, k, v, cu_q, cu_k, causal):
    """Per-sequence dense softmax attention on the packed arrays."""
    outs = []
    for b in range(len(cu_q) - 1):
        qs = q[cu_q[b]:cu_q[b + 1]]           # [sq, H, D]
        ks = k[cu_k[b]:cu_k[b + 1]]
        vs = v[cu_k[b]:cu_k[b + 1]]
        logits = np.einsum("qhd,khd->hqk", qs, ks) / np.sqrt(D)
        if causal:
            sq, sk = qs.shape[0], ks.shape[0]
            mask = np.tril(np.ones((sq, sk), bool))
            logits = np.where(mask[None], logits, -1e30)
        p = np.exp(logits - logits.max(-1, keepdims=True))
        p = p / p.sum(-1, keepdims=True)
        outs.append(np.einsum("hqk,khd->qhd", p, vs))
    return np.concatenate(outs, axis=0)


@pytest.mark.parametrize("causal", [False, True])
def test_unpadded_matches_dense(causal):
    rng = np.random.RandomState(0)
    lens = [5, 1, 9, 3]
    q, cu = _packed(lens, H, rng)
    k, _ = _packed(lens, HKV, rng)
    v, _ = _packed(lens, HKV, rng)
    out, _ = F.flash_attn_unpadded(
        paddle.to_tensor(q), paddle.to_tensor(k), paddle.to_tensor(v),
        paddle.to_tensor(cu), paddle.to_tensor(cu),
        max_seqlen_q=max(lens), max_seqlen_k=max(lens), causal=causal)
    ref = _dense_ref(q, k, v, cu, cu, causal)
    np.testing.assert_allclose(out.numpy(), ref, rtol=2e-5, atol=2e-5)


def test_unpadded_cross_lengths():
    """Different q/k packing (cross-attention style)."""
    rng = np.random.RandomState(1)
    lens_q, lens_k = [4, 7], [6, 2]
    q, cu_q = _packed(lens_q, H, rng)
    k, cu_k = _packed(lens_k, H, rng)
    v, _ = _packed(lens_k, H, rng)
    out, _ = F.flash_attn_unpadded(
        paddle.to_tensor(q), paddle.to_tensor(k), paddle.to_tensor(v),
        paddle.to_tensor(cu_q), paddle.to_tensor(cu_k),
        max_seqlen_q=max(lens_q), max_seqlen_k=max(lens_k))
    ref = _dense_ref(q, k, v, cu_q, cu_k, False)
    np.testing.assert_allclose(out.numpy(), ref, rtol=2e-5, atol=2e-5)


def test_unpadded_backward_no_cross_sequence_leak():
    """Grad wrt q of a loss on sequence 0 must be zero on other sequences
    (the segment mask really isolates sequences), and grads must match the
    dense per-sequence computation numerically."""
    rng = np.random.RandomState(2)
    lens = [6, 4]
    qn, cu = _packed(lens, H, rng)
    kn, _ = _packed(lens, H, rng)
    vn, _ = _packed(lens, H, rng)
    q = paddle.to_tensor(qn); q.stop_gradient = False
    k = paddle.to_tensor(kn); k.stop_gradient = False
    v = paddle.to_tensor(vn); v.stop_gradient = False
    out, _ = F.flash_attn_unpadded(
        q, k, v, paddle.to_tensor(cu), paddle.to_tensor(cu),
        max_seqlen_q=max(lens), max_seqlen_k=max(lens), causal=True)
    # loss touches only sequence 0 rows
    loss = (out[:lens[0]] ** 2).sum()
    loss.backward()
    gq = q.grad.numpy()
    assert np.abs(gq[:lens[0]]).max() > 0
    np.testing.assert_allclose(gq[lens[0]:], 0.0, atol=1e-7)
    gk = k.grad.numpy()
    np.testing.assert_allclose(gk[lens[0]:], 0.0, atol=1e-7)

    # numeric check of one grad entry via finite differences
    eps = 1e-3
    qp = qn.copy(); qp[0, 0, 0] += eps
    qm = qn.copy(); qm[0, 0, 0] -= eps

    def f(qq):
        o, _ = F.flash_attn_unpadded(
            paddle.to_tensor(qq), paddle.to_tensor(kn), paddle.to_tensor(vn),
            paddle.to_tensor(cu), paddle.to_tensor(cu),
            max_seqlen_q=max(lens), max_seqlen_k=max(lens), causal=True)
        return float((o[:lens[0]] ** 2).sum().numpy())

    fd = (f(qp) - f(qm)) / (2 * eps)
    np.testing.assert_allclose(gq[0, 0, 0], fd, rtol=2e-2, atol=1e-3)


@pytest.mark.parametrize("causal", [False, True])
def test_unpadded_kernel_branch(monkeypatch, causal):
    """The TPU kernel branch of flash_attn_unpadded (routing, limits gate,
    self_attn identity detection, Tensor/_run_op integration), forced on
    under CPU interpret mode."""
    import paddle_tpu.nn.functional.attention as A
    monkeypatch.setattr(A, "_use_pallas", lambda q: True)
    rng = np.random.RandomState(4)
    lens = [70, 58]
    q, cu = _packed(lens, H, rng)
    k, _ = _packed(lens, H, rng)
    v, _ = _packed(lens, H, rng)
    qt = paddle.to_tensor(q); qt.stop_gradient = False
    cut = paddle.to_tensor(cu)
    out, _ = F.flash_attn_unpadded(
        qt, paddle.to_tensor(k), paddle.to_tensor(v), cut, cut,
        max_seqlen_q=max(lens), max_seqlen_k=max(lens), causal=causal)
    ref = _dense_ref(q, k, v, cu, cu, causal)
    np.testing.assert_allclose(out.numpy(), ref, rtol=2e-4, atol=2e-4)
    # backward through the kernel branch (custom_vjp + None cotangents for
    # the integer cu args)
    loss = (out ** 2).sum()
    loss.backward()
    assert np.isfinite(qt.grad.numpy()).all()


def test_unpadded_gqa_heads():
    """Hkv < H: kv heads broadcast over query-head groups."""
    rng = np.random.RandomState(3)
    lens = [5, 3]
    q, cu = _packed(lens, 4, rng)
    k, _ = _packed(lens, 2, rng)
    v, _ = _packed(lens, 2, rng)
    out, _ = F.flash_attn_unpadded(
        paddle.to_tensor(q), paddle.to_tensor(k), paddle.to_tensor(v),
        paddle.to_tensor(cu), paddle.to_tensor(cu),
        max_seqlen_q=max(lens), max_seqlen_k=max(lens))
    krep = np.repeat(k, 2, axis=1)
    vrep = np.repeat(v, 2, axis=1)
    ref = _dense_ref(q, krep, vrep, cu, cu, False)
    np.testing.assert_allclose(out.numpy(), ref, rtol=2e-5, atol=2e-5)
