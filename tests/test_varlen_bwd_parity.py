"""Varlen BACKWARD grad parity vs the dense masked reference across
adversarial pack layouts (PR 4 numerics contract — see PARITY.md).

The fused flat-schedule backward replaced the rectangular dKV/dQ grids;
these tests pin its gradients on exactly the layouts that stress the
live-tile schedule: single-token segments, segment ends on tile
boundaries, a padded tail, empty pack entries, and cross-attention
packs whose k side has zero-token segments (the dq coverage fix).
Tolerances are pinned: fwd 2e-4, grads 2e-3 (f32 inputs, CPU interpret).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu  # noqa: F401  (configures CPU default device in tests)
from paddle_tpu.ops import flash_varlen as fv
from paddle_tpu.ops.flash_varlen import flash_varlen_attention

D = 32
SCALE = 1.0 / np.sqrt(D)
GRAD_TOL = 2e-3

LAYOUTS = {
    # every segment is one token: every live tile is almost all dead area
    "single_token": [1] * 9,
    # segment ends exactly on 128-tile boundaries: first/last flags flip
    # at every tile edge, no partial tiles
    "tile_boundary": [128, 256, 128],
    # total 161 -> padded to 256: a trailing tile that is >half padding
    "pad_tail": [100, 61],
    # zero-length pack entries between real segments
    "empty_segments": [64, 0, 100, 0, 31],
    # pathological mix: singletons around tile-sized and tile-crossing
    "mixed": [1, 128, 3, 257, 1],
}


def _packed(lens, heads, rng):
    total = sum(lens)
    x = rng.randn(total, heads, D).astype(np.float32)
    cu = np.concatenate([[0], np.cumsum(lens)]).astype(np.int32)
    return jnp.asarray(x), jnp.asarray(cu)


def _ref_loss(cu, causal, scale):
    cu_np = np.asarray(cu)

    def loss(q, k, v):
        outs = []
        for b in range(len(cu_np) - 1):
            lo, hi = int(cu_np[b]), int(cu_np[b + 1])
            if lo == hi:
                continue
            qs, ks, vs = q[lo:hi], k[lo:hi], v[lo:hi]
            logits = jnp.einsum("qhd,khd->hqk", qs, ks) * scale
            if causal:
                m = jnp.tril(jnp.ones((hi - lo, hi - lo), bool))
                logits = jnp.where(m[None], logits, -1e30)
            p = jax.nn.softmax(logits, axis=-1)
            outs.append(jnp.einsum("hqk,khd->qhd", p, vs))
        return (jnp.concatenate(outs, 0) ** 2).sum()

    return loss


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("name", sorted(LAYOUTS))
def test_bwd_parity_adversarial_layouts(name, causal):
    lens = LAYOUTS[name]
    rng = np.random.RandomState(sum(map(ord, name)) % 1000)
    q, cu = _packed(lens, 2, rng)
    k, _ = _packed(lens, 2, rng)
    v, _ = _packed(lens, 2, rng)

    def loss(q, k, v):
        o = flash_varlen_attention(q, k, v, cu, cu, SCALE, causal,
                                   self_attn=True, block_q=128, block_k=128)
        return (o ** 2).sum()

    got = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    want = jax.grad(_ref_loss(cu, causal, SCALE), argnums=(0, 1, 2))(q, k, v)
    # the reference skips empty segments, but they hold no tokens so the
    # packed grad arrays line up 1:1
    for g, r, nm in zip(got, want, "qkv"):
        np.testing.assert_allclose(np.asarray(g), np.asarray(r),
                                   rtol=GRAD_TOL, atol=GRAD_TOL,
                                   err_msg=f"{name} d{nm}")


def test_bwd_cross_attn_empty_k_segment_dq_is_zero():
    """Cross-attn q tiles whose segment has ZERO k tokens are never
    presented by the k-major fused schedule — their dq comes from the
    in-graph coverage fix and must be exactly zero (which IS the true
    gradient: their output is all-padding)."""
    lens_q, lens_k = [40, 8, 30], [64, 0, 32]
    rng = np.random.RandomState(29)
    q, cu_q = _packed(lens_q, 2, rng)
    k, cu_k = _packed(lens_k, 2, rng)
    v, _ = _packed(lens_k, 2, rng)

    def loss(q, k, v):
        o = flash_varlen_attention(q, k, v, cu_q, cu_k, SCALE, False,
                                   self_attn=False, block_q=128, block_k=128)
        return (o ** 2).sum()

    gq, gk, gv = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    assert np.isfinite(np.asarray(gq)).all()
    # segment 1 (q rows 40:48) attends to zero keys -> dq exactly 0
    np.testing.assert_array_equal(np.asarray(gq[40:48]), 0.0)
    # the populated segments still get real gradients
    assert float(jnp.abs(gq[:40]).max()) > 0
    assert float(jnp.abs(gq[48:]).max()) > 0

    def ref(q, k, v):
        outs = []
        cuq_np, cuk_np = np.asarray(cu_q), np.asarray(cu_k)
        for b in range(len(lens_q)):
            qs = q[int(cuq_np[b]):int(cuq_np[b + 1])]
            ks = k[int(cuk_np[b]):int(cuk_np[b + 1])]
            vs = v[int(cuk_np[b]):int(cuk_np[b + 1])]
            if ks.shape[0] == 0:
                outs.append(jnp.zeros_like(qs))
                continue
            p = jax.nn.softmax(
                jnp.einsum("qhd,khd->hqk", qs, ks) * SCALE, axis=-1)
            outs.append(jnp.einsum("hqk,khd->qhd", p, vs))
        return (jnp.concatenate(outs, 0) ** 2).sum()

    want = jax.grad(ref, argnums=(0, 1, 2))(q, k, v)
    for g, r, nm in zip((gq, gk, gv), want, "qkv"):
        np.testing.assert_allclose(np.asarray(g), np.asarray(r),
                                   rtol=GRAD_TOL, atol=GRAD_TOL,
                                   err_msg=f"d{nm}")


@pytest.mark.parametrize("causal", [False, True])
def test_fused_bwd_bitwise_equals_split_fallback(causal):
    """The fused one-pass backward must be BITWISE equal to the two-kernel
    split fallback at the same blocks (the bias add is absorbed
    identically in f32; matmul order per tile is identical). Forcing the
    split path via the VMEM budget knob keeps blocks and schedule fixed
    so only the fusion differs."""
    lens = [60, 130, 200, 40]
    rng = np.random.RandomState(31)
    q, cu = _packed(lens, 2, rng)
    k, _ = _packed(lens, 2, rng)
    v, _ = _packed(lens, 2, rng)

    def loss(q, k, v):
        o = flash_varlen_attention(q, k, v, cu, cu, SCALE, causal,
                                   self_attn=True, block_q=128, block_k=128)
        return (o.astype(jnp.float32) ** 2).sum()

    g_fused = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    budget = fv._FUSED_BWD_VMEM_BUDGET
    try:
        fv._FUSED_BWD_VMEM_BUDGET = 0   # nothing fits -> split kernels
        g_split = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    finally:
        fv._FUSED_BWD_VMEM_BUDGET = budget
    for gf, gs, nm in zip(g_fused, g_split, "qkv"):
        np.testing.assert_array_equal(np.asarray(gf), np.asarray(gs),
                                      err_msg=f"d{nm}")


def test_bwd_fused_nh_selection_pins():
    """Head-fusion grouping for the fused backward: bench shape groups 4
    heads, long packs fall back to split (nh=0), tiny packs group all 8."""
    # bench pack shape: h=8, bf16, 512x512 stacked blocks, 16k tokens
    assert fv._bwd_fused_nh(8, 2, 128, 512, 512, 16384) == 4
    # 128k-token pack: the dq scratch alone blows the budget -> split
    assert fv._bwd_fused_nh(8, 2, 128, 1024, 1024, 131072) == 0
    # small pack, small head_dim: everything fits, fuse all heads
    assert fv._bwd_fused_nh(8, 4, 32, 128, 128, 1024) == 8
    # grouping must divide h
    assert fv._bwd_fused_nh(6, 4, 32, 128, 128, 1024) in (1, 2, 6)
