"""Vision model zoo: forward shape + trainability checks (SURVEY.md §2b).

Small inputs keep CPU runtime low; each model runs a forward pass and the
flagship ones also take one optimizer step to prove the graph is trainable.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.vision import models

R = np.random.RandomState(0)


def _img(n=1, s=64):
    return paddle.to_tensor(R.rand(n, 3, s, s).astype(np.float32))


# deep-stack XLA compiles dominate the tier-1 CPU budget: one forward per
# model family stays in tier-1, the redundant/deepest variants run as slow
@pytest.mark.parametrize("builder,classes", [
    pytest.param(models.alexnet, 10, marks=pytest.mark.slow),
    pytest.param(models.squeezenet1_0, 10, marks=pytest.mark.slow),
    (models.squeezenet1_1, 10),
    pytest.param(models.mobilenet_v1, 10, marks=pytest.mark.slow),
    pytest.param(models.mobilenet_v3_small, 10, marks=pytest.mark.slow),
    (models.shufflenet_v2_x0_25, 10),
])
def test_small_model_forward(builder, classes):
    m = builder(num_classes=classes)
    m.eval()
    out = m(_img(2, 64))
    assert list(out.shape) == [2, classes]
    assert np.isfinite(out.numpy()).all()


@pytest.mark.parametrize("builder", [
    pytest.param(models.densenet121, marks=pytest.mark.slow),
    pytest.param(models.googlenet, marks=pytest.mark.slow),
    models.shufflenet_v2_x1_0,
])
def test_medium_model_forward(builder):
    m = builder(num_classes=7)
    m.eval()
    out = m(_img(1, 64))
    assert list(out.shape) == [1, 7]
    assert np.isfinite(out.numpy()).all()


@pytest.mark.slow
def test_inception_v3_forward():
    # stem requires >= 75px input
    m = models.inception_v3(num_classes=5)
    m.eval()
    out = m(paddle.to_tensor(R.rand(1, 3, 96, 96).astype(np.float32)))
    assert list(out.shape) == [1, 5]


@pytest.mark.slow   # deep conv backward compile ~12s on the tier-1 CPU box
def test_zoo_model_trains():
    m = models.squeezenet1_1(num_classes=4)
    m.train()
    opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=m.parameters())
    x = _img(2, 64)
    y = paddle.to_tensor(np.array([0, 1]))
    losses = []
    for _ in range(3):
        loss = paddle.nn.functional.cross_entropy(m(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss.numpy()))
    assert losses[-1] < losses[0]


def test_state_dict_roundtrip():
    m = models.squeezenet1_1(num_classes=3)
    sd = m.state_dict()
    m2 = models.squeezenet1_1(num_classes=3)
    m2.set_state_dict(sd)
    x = _img(1, 64)
    m.eval(); m2.eval()
    np.testing.assert_allclose(m(x).numpy(), m2(x).numpy(), atol=1e-6)
