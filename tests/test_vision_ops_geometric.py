"""vision.ops (nms/iou/roi_align/yolo_box) + geometric segment ops."""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu.geometric import (segment_max, segment_mean, segment_sum,
                                  send_u_recv, send_uv)
from paddle_tpu.vision.ops import box_iou, nms, roi_align

R = np.random.RandomState(9)


def t(x):
    return paddle.to_tensor(x)


class TestBoxOps:
    def test_box_iou(self):
        a = np.array([[0, 0, 2, 2], [1, 1, 3, 3]], np.float32)
        b = np.array([[0, 0, 2, 2], [2, 2, 4, 4]], np.float32)
        iou = box_iou(t(a), t(b)).numpy()
        assert abs(iou[0, 0] - 1.0) < 1e-6
        assert iou[0, 1] == 0.0
        assert abs(iou[1, 0] - (1 / 7)) < 1e-6  # 1 overlap / (4+4-1)

    def test_nms_suppresses(self):
        boxes = np.array([[0, 0, 10, 10], [1, 1, 11, 11], [50, 50, 60, 60]],
                         np.float32)
        scores = np.array([0.9, 0.8, 0.7], np.float32)
        keep = nms(t(boxes), iou_threshold=0.5, scores=t(scores)).numpy()
        assert keep.tolist() == [0, 2]

    def test_nms_category_aware(self):
        boxes = np.array([[0, 0, 10, 10], [1, 1, 11, 11]], np.float32)
        scores = np.array([0.9, 0.8], np.float32)
        cats = np.array([0, 1])
        keep = nms(t(boxes), iou_threshold=0.5, scores=t(scores),
                   category_idxs=t(cats), categories=[0, 1]).numpy()
        assert sorted(keep.tolist()) == [0, 1]  # different class: both kept

    def test_roi_align_uniform(self):
        # constant feature map -> every pooled value equals the constant
        x = np.full((1, 2, 8, 8), 3.0, np.float32)
        boxes = np.array([[0, 0, 8, 8], [2, 2, 6, 6]], np.float32)
        out = roi_align(t(x), t(boxes), t(np.array([2])), output_size=2,
                        spatial_scale=1.0)
        assert list(out.shape) == [2, 2, 2, 2]
        np.testing.assert_allclose(out.numpy(), 3.0, atol=1e-5)

    def test_roi_align_gradient_region(self):
        # linear ramp along x: pooled values must increase along x
        ramp = np.tile(np.arange(8, dtype=np.float32), (8, 1))
        x = ramp[None, None]
        boxes = np.array([[0, 0, 8, 8]], np.float32)
        out = roi_align(t(x), t(boxes), t(np.array([1])),
                        output_size=4).numpy()[0, 0]
        assert (np.diff(out.mean(0)) > 0).all()

    def test_yolo_box_shapes(self):
        from paddle_tpu.vision.ops import yolo_box
        b, na, cls, h = 2, 3, 5, 4
        x = R.randn(b, na * (5 + cls), h, h).astype(np.float32)
        img = np.array([[64, 64], [32, 32]], np.int32)
        boxes, scores = yolo_box(t(x), t(img), anchors=[10, 13, 16, 30, 33, 23],
                                 class_num=cls, conf_thresh=0.01,
                                 downsample_ratio=8)
        assert list(boxes.shape) == [b, na * h * h, 4]
        assert list(scores.shape) == [b, na * h * h, cls]


class TestGeometric:
    def test_segment_ops(self):
        data = np.array([[1., 2.], [3., 4.], [5., 6.], [7., 8.]], np.float32)
        seg = np.array([0, 0, 1, 1])
        np.testing.assert_allclose(
            segment_sum(t(data), t(seg)).numpy(), [[4, 6], [12, 14]])
        np.testing.assert_allclose(
            segment_mean(t(data), t(seg)).numpy(), [[2, 3], [6, 7]])
        np.testing.assert_allclose(
            segment_max(t(data), t(seg)).numpy(), [[3, 4], [7, 8]])

    def test_send_u_recv(self):
        x = np.array([[1.], [2.], [4.]], np.float32)
        src = np.array([0, 1, 2, 0])
        dst = np.array([1, 2, 0, 2])
        out = send_u_recv(t(x), t(src), t(dst), reduce_op="sum").numpy()
        # node1 <- x0; node2 <- x1 + x0; node0 <- x2
        np.testing.assert_allclose(out, [[4.], [1.], [3.]])
        out_max = send_u_recv(t(x), t(src), t(dst), reduce_op="max").numpy()
        np.testing.assert_allclose(out_max, [[4.], [1.], [2.]])

    def test_send_uv(self):
        x = np.array([[1.], [2.]], np.float32)
        y = np.array([[10.], [20.]], np.float32)
        src = np.array([0, 1])
        dst = np.array([1, 0])
        out = send_uv(t(x), t(y), t(src), t(dst), message_op="add").numpy()
        np.testing.assert_allclose(out, [[21.], [12.]])
