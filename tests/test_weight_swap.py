"""Live weight push + engine preemption (InferenceEngine.swap_weights).

The serving-side contract pinned here (ISSUE 13):

  * a mid-serve swap applies at an iteration boundary — the drain point
    where the previous decode has synced its tokens — and never drops or
    corrupts a request;
  * swapping in IDENTICAL weights is bit-identical: the token stream
    matches an unswapped run exactly;
  * requests served entirely after a swap follow the NEW weights
    (greedy parity against the new params), earlier requests keep their
    already-generated prefix — the standard live-update contract;
  * `source` may be an in-memory tree, a checkpoint dir, or a
    CheckpointManager root (newest complete checkpoint wins);
  * engine preemption (flag, SIGTERM, injected) stops at an iteration
    boundary with queue/active state intact; a re-driven engine finishes
    every request with the same tokens as an uninterrupted run.

Tiny llama, pallas interpret mode on CPU, deterministic traces.
"""
import os
import signal

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.distributed.checkpoint import save_load as sl
from paddle_tpu.distributed.checkpoint.manager import CheckpointManager
from paddle_tpu.inference import InferenceEngine, Request, ServeConfig
from paddle_tpu.models.llama import (greedy_generate, init_llama_params,
                                     llama_tiny)
from paddle_tpu.ops import _common
from paddle_tpu.testing import faults


@pytest.fixture(autouse=True)
def _interpret():
    with _common.interpret_mode(True):
        yield


@pytest.fixture(scope="module")
def model():
    cfg = llama_tiny(vocab=96, hidden=64, layers=1, heads=4, kv_heads=2,
                     seq=512)
    return cfg, init_llama_params(cfg, seed=3), init_llama_params(cfg,
                                                                  seed=11)


def _serve():
    return ServeConfig(block_size=128, num_blocks=10, max_batch=2,
                       prefill_chunk=32, max_seq_len=512)


def _prompts():
    rng = np.random.RandomState(0)
    return [rng.randint(1, 96, size=n).tolist() for n in (7, 130)]


def _copy(tree):
    # fresh containers, same leaves: swap_fill mutates dicts in place and
    # module-scoped fixture params must never be touched by a swap
    return jax.tree_util.tree_map(lambda a: a, tree)


def _greedy(cfg, params, prompt, n_new):
    with _common.interpret_mode(True):
        out = greedy_generate(params, jnp.asarray([prompt], jnp.int32), cfg,
                              n_new)
    return np.asarray(out)[0].tolist()


def _toks(eng):
    return {s.req.request_id: s.tokens for s in eng.finished}


def _run(params, cfg, reqs, **kw):
    eng = InferenceEngine(_copy(params), cfg, _serve(), record_events=True,
                          **kw)
    stats = eng.run(reqs, deterministic=True)
    return eng, stats


# -- the swap contract -------------------------------------------------------

def test_mid_serve_identical_swap_is_bit_identical(model):
    cfg, params, _ = model
    prompts = _prompts()
    mk = lambda: [Request(p, max_new_tokens=5, arrival=float(i))
                  for i, p in enumerate(prompts)]
    base, _ = _run(params, cfg, mk())

    eng = InferenceEngine(_copy(params), cfg, _serve(), record_events=True)
    sched = eng.swap_weights(_copy(params), at_iteration=3)
    assert sched == {"scheduled_at": 3}
    stats = eng.run(mk(), deterministic=True)

    assert _toks(eng) == _toks(base)  # bit-identical token streams
    assert stats["requests"] == 2 and stats["unfinished"] == 0
    assert stats["weight_swaps"] == 1 and eng.swaps == 1
    # the swap really happened mid-serve, at the scheduled drain point,
    # with work in flight — not on an idle engine
    assert eng.last_swap["iteration"] == 2  # top of the step becoming 3
    assert (eng.last_swap["in_flight_running"]
            + eng.last_swap["in_flight_prefill"]) >= 1
    assert eng.pool.used_blocks == 0  # no leaks through the swap


def test_requests_after_swap_follow_new_weights(model):
    cfg, params, params2 = model
    prompt = _prompts()[0]  # 7 tokens: one prefill chunk
    old_ref = _greedy(cfg, params, prompt, 4)
    new_ref = _greedy(cfg, params2, prompt, 4)
    assert old_ref != new_ref  # otherwise this test proves nothing

    eng = InferenceEngine(_copy(params), cfg, _serve(), record_events=True)
    eng.swap_weights(_copy(params2), at_iteration=6)
    reqs = [Request(prompt, max_new_tokens=4, arrival=0.0),   # pre-swap
            Request(prompt, max_new_tokens=4, arrival=8.0)]   # post-swap
    stats = eng.run(reqs, deterministic=True)

    assert stats["requests"] == 2 and stats["unfinished"] == 0
    got = {s.req.request_id: s.generated for s in eng.finished}
    assert got[0] == old_ref  # finished before the swap landed
    assert got[1] == new_ref  # served end-to-end by the new weights


def test_swap_from_checkpoint_dir_and_manager_root(model, tmp_path):
    cfg, params, params2 = model
    prompt = _prompts()[0]
    new_ref = _greedy(cfg, params2, prompt, 4)

    # a bare save_state_dict dir
    ck = str(tmp_path / "ck")
    sl.save_state_dict(_copy(params2), ck)
    eng = InferenceEngine(_copy(params), cfg, _serve())
    stats = eng.swap_weights(ck)
    assert stats["n_leaves"] == len(jax.tree_util.tree_leaves(params2))
    assert stats["source"] == os.path.abspath(ck)
    eng.run([Request(prompt, max_new_tokens=4, arrival=0.0)],
            deterministic=True)
    assert eng.finished[0].generated == new_ref

    # a CheckpointManager root: newest complete checkpoint, nested under
    # the TrainStep state dict's "params" key
    mgr = CheckpointManager(str(tmp_path / "root"), keep=2)
    mgr.save({"params": _copy(params), "step": 1}, 1, block=True)
    mgr.save({"params": _copy(params2), "step": 2}, 2, block=True)
    eng2 = InferenceEngine(_copy(params), cfg, _serve())
    stats2 = eng2.swap_weights(str(tmp_path / "root"))
    assert stats2["source"] == mgr.step_dir(2)
    eng2.run([Request(prompt, max_new_tokens=4, arrival=0.0)],
             deterministic=True)
    assert eng2.finished[0].generated == new_ref


def test_swap_rejects_mismatched_trees(model):
    cfg, params, _ = model
    eng = InferenceEngine(_copy(params), cfg, _serve())
    bad = _copy(params)
    bad.pop(sorted(bad)[0])
    with pytest.raises(ValueError, match="param tree mismatch"):
        eng.swap_weights(bad)

    leaves, treedef = jax.tree_util.tree_flatten(_copy(params))
    i = next(j for j, l in enumerate(leaves) if l.ndim >= 1)
    leaves[i] = leaves[i][..., :1]
    with pytest.raises(ValueError, match="shape mismatch"):
        eng.swap_weights(jax.tree_util.tree_unflatten(treedef, leaves))
    # a rejected swap leaves the engine serving the OLD weights intact
    prompt = _prompts()[0]
    eng.run([Request(prompt, max_new_tokens=4, arrival=0.0)],
            deterministic=True)
    assert eng.finished[0].generated == _greedy(cfg, params, prompt, 4)
    assert eng.swaps == 0


def test_swap_under_preemption_storm_drops_nothing(model, monkeypatch):
    """Forced evictions raining on the scheduler while a (identical)
    swap lands mid-serve: every request still finishes with the greedy
    reference tokens (recompute semantics), nothing leaks."""
    monkeypatch.setenv(faults.ENV_FAULTS, "1")
    cfg, params, _ = model
    rng = np.random.RandomState(1)
    prompts = [rng.randint(1, 96, size=120).tolist() for _ in range(3)]
    serve = ServeConfig(block_size=128, num_blocks=5, max_batch=3,
                        prefill_chunk=64, max_seq_len=256)
    eng = InferenceEngine(_copy(params), cfg, serve, record_events=True)
    eng.swap_weights(_copy(params), at_iteration=6)
    reqs = [Request(p, max_new_tokens=16, arrival=float(i))
            for i, p in enumerate(prompts)]
    try:
        with faults.scope("serve.preempt_storm", "fire", p=0.25, seed=5,
                          max_fires=None) as plan:
            stats = eng.run(reqs, deterministic=True)
    finally:
        faults.disarm()
    assert plan.fired >= 1, "the storm never struck — weaken nothing"
    assert stats["requests"] == 3 and stats["unfinished"] == 0
    assert eng.swaps == 1
    assert all(len(s.generated) == 16 for s in eng.finished)
    for i, p in enumerate(prompts):
        got = [s for s in eng.finished
               if s.req.request_id == i][0].generated
        assert got == _greedy(cfg, params, p, 16), f"request {i}"
    assert eng.pool.used_blocks == 0


# -- engine preemption -------------------------------------------------------

def test_injected_preemption_stops_cleanly_and_resumes(model, monkeypatch,
                                                       tmp_path):
    """A preemption three iterations in: run() exits at the boundary with
    the post-mortem dumped and all state intact; re-driving the same
    engine finishes every request bit-identically to an uninterrupted
    run."""
    from paddle_tpu.observability import load_dump
    monkeypatch.setenv(faults.ENV_FAULTS, "1")
    monkeypatch.setenv("PADDLE_TPU_TELEMETRY_DIR", str(tmp_path))
    cfg, params, _ = model
    prompts = _prompts()
    mk = lambda: [Request(p, max_new_tokens=5, arrival=float(i))
                  for i, p in enumerate(prompts)]
    base, _ = _run(params, cfg, mk())

    eng = InferenceEngine(_copy(params), cfg, _serve(), record_events=True,
                          flight_recorder=True)
    try:
        with faults.scope("serve.preempt", "fire", nth=3):
            st1 = eng.run(mk(), deterministic=True)
    finally:
        faults.disarm()
    assert st1["preempted"] is True
    assert any(e[1] == "preempt_stop" for e in eng.events)
    assert st1["unfinished"] >= 1  # stopped with work still queued/active
    assert len(eng.recorder.dumped) == 1
    payload = load_dump(eng.recorder.dumped[0])
    assert payload["reason"] == "preemption" and payload["source"] == "engine"

    # the successor re-drives the SAME engine state: nothing was dropped
    st2 = eng.run([], deterministic=True)
    assert st2["requests"] == 2 and st2["unfinished"] == 0
    assert _toks(eng) == _toks(base)
    assert eng.pool.used_blocks == 0


def test_sigterm_preempts_then_cleared_engine_serves(model):
    cfg, params, _ = model
    prompts = _prompts()
    eng = InferenceEngine(_copy(params), cfg, _serve())
    reqs = [Request(p, max_new_tokens=5, arrival=0.0) for p in prompts]
    eng.install_preemption_handler()
    try:
        os.kill(os.getpid(), signal.SIGTERM)
        stats = eng.run(reqs, deterministic=True)
    finally:
        eng.uninstall_preemption_handler()
    # the flag was already set: not a single request was admitted or lost
    assert stats["preempted"] is True and len(eng.finished) == 0
    eng.clear_preemption()
    stats2 = eng.run(reqs, deterministic=True)
    assert stats2["requests"] == 2 and stats2["unfinished"] == 0
    for i, p in enumerate(prompts):
        got = [s for s in eng.finished
               if s.req.request_id == i][0].generated
        assert got == _greedy(cfg, params, p, 5), f"request {i}"


if __name__ == "__main__":
    pytest.main([__file__, "-q"])
